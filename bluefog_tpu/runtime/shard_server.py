"""One control-plane shard server, as a standalone OS process.

The sharded control plane (docs/fault_tolerance.md, "Control-plane
sharding & failover") runs N of these; clients route keys across them with
:class:`bluefog_tpu.runtime.router.ShardRouter`. Launched by
``bfrun --cp-shards N``, by ``scripts/cp_soak.py``, and by the chaos tests
(which SIGKILL it mid-job on purpose):

    python bluefog_tpu/runtime/shard_server.py --port P --world W [--shard I]

Run BY FILE PATH it bootstraps lean — the relative imports below resolve
without executing ``bluefog_tpu/__init__`` (which imports jax): a shard
server must start in milliseconds, hold no accelerator state, and cost a
few MB of RSS, because the churn soak starts and kills them in bulk.
Importable normally (``bluefog_tpu.runtime.shard_server``) for in-process
use.

Prints ``BF_SHARD_READY <port>`` on stdout once serving (the spawn-side
readiness handshake), then blocks until SIGTERM/SIGINT. The job secret
rides ``BLUEFOG_CP_SECRET`` exactly as for the single-server plane, and
the server self-publishes its effective mailbox cap under
``bf.cp.mailbox_cap_bytes`` so attach-time agreement checks can reject a
mixed-cap cluster loudly (every shard must publish its OWN value — a
router must never write this key, or a mismatch would be masked).

Durable-plane peer wiring (r16): with ``--expect-peers`` the handshake is
two-phase — the server prints ``BF_SHARD_PORT <port>`` first, the spawner
collects every shard's port and writes one ``BF_SHARD_PEERS
host:port,host:port,...`` line to each shard's stdin, and only then does
the server configure its ring successor (WAL replication,
``BLUEFOG_CP_REPLICATION``) and print the READY line. Ephemeral ports
(``--port 0``) therefore need no pre-agreed port plan. ``--rejoin``
(requires an explicit ``--port`` — the routers hold the old endpoint)
additionally pulls a state snapshot from the ring successor, loads it,
and publishes the next EVEN liveness generation under
``bf.cp.shard_dead.<i>`` so every router moves the keyspace back.
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__" and __package__ in (None, ""):
    # Lean bootstrap: register dummy parent packages so the relative
    # imports below resolve WITHOUT executing bluefog_tpu/__init__ (jax)
    # or bluefog_tpu/runtime/__init__ (state -> jax).
    import types

    _here = os.path.dirname(os.path.abspath(__file__))
    _pkg = os.path.dirname(_here)
    # replace sys.path[0] (this script's directory — it would shadow the
    # stdlib `logging` with runtime/logging.py) with the repo root
    sys.path[0] = os.path.dirname(_pkg)
    for _name, _path in (("bluefog_tpu", _pkg),
                         ("bluefog_tpu.runtime", _here)):
        if _name not in sys.modules:
            _mod = types.ModuleType(_name)
            _mod.__path__ = [_path]
            sys.modules[_name] = _mod
    __package__ = "bluefog_tpu.runtime"

import argparse
import signal
import threading
import time

from .config import knob_env
from .logging import logger
from .native import ControlPlaneClient, ControlPlaneServer

READY_MARKER = "BF_SHARD_READY"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bf-shard-server",
        description="Serve one shard of the bluefog control plane.")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port to bind (0 = ephemeral, reported on the "
                        "READY line)")
    p.add_argument("--world", type=int, default=1,
                   help="number of controller processes in the job "
                        "(barrier arity; must match every shard)")
    p.add_argument("--shard", type=int, default=0,
                   help="this shard's index (logging only; routing is "
                        "decided client-side by key hash)")
    p.add_argument("--mailbox-max-mb", type=float, default=None,
                   help="per-mailbox byte cap (default: the "
                        "BLUEFOG_CP_MAILBOX_MAX_MB registry knob)")
    p.add_argument("--expect-peers", action="store_true",
                   help="two-phase start: print BF_SHARD_PORT, read one "
                        "'BF_SHARD_PEERS host:port,...' line from stdin, "
                        "wire the ring successor (WAL replication), then "
                        "print the READY line")
    p.add_argument("--peers", default=None, metavar="HOST:PORT,...",
                   help="explicit ring endpoint list (all shards, in "
                        "index order) when ports are known up front; "
                        "alternative to --expect-peers")
    p.add_argument("--rejoin", action="store_true",
                   help="restarted-shard catch-up: pull a state snapshot "
                        "from the ring successor, load it, and publish "
                        "the next even liveness generation before READY "
                        "(requires --port and a peer list)")
    return p


def _parse_peers(spec: str):
    out = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port = item.rpartition(":")
        out.append((host, int(port)))
    return out


def _rejoin_catch_up(srv, idx: int, peers, secret: str) -> None:
    """Restarted-shard catch-up, two pulls with distinct roles:

    1. From the ring SUCCESSOR — this shard's own keyspace, which the
       successor replicated and has been serving since the death. The
       load also RESUMES this shard's WAL numbering (``adopt_wal``) from
       the fence the successor holds against this shard's stream: a
       restart back at zero would leave every post-rejoin record at or
       below that stale fence — silently dropped-and-acked by the
       successor, i.e. lost on this shard's next death.
    2. From the ring PREDECESSOR — ITS keyspace (this shard's replica
       role). The pull carries the receiver flag (``rearm``): serving it
       re-arms the predecessor's degraded stream from that exact cut,
       and ``set_fence`` adopts the cut's fence so the resumed stream
       skips records already folded in — gap-free.

    For a two-shard ring both roles are the same endpoint, so one
    unfiltered receiver-flagged pull carries everything at a single cut
    (two filtered pulls would open a gap between their cuts)."""
    n = len(peers)
    succ = (idx + 1) % n
    pred = (idx - 1) % n
    deadline = time.monotonic() + float(knob_env("BLUEFOG_CP_REJOIN_TIMEOUT"))
    last = None
    while True:
        try:
            host, port = peers[succ]
            cl = ControlPlaneClient(host, port, 0, secret=secret, streams=1)
            try:
                if n <= 2:
                    # successor == predecessor: one cut carries both the
                    # served keyspace and the replica keyspace; the fence,
                    # the WAL resume, and the stream re-arm all anchor to
                    # that single cut
                    srv.load_snapshot(cl.snapshot(rearm=True),
                                      set_fence=True, adopt_wal=True)
                else:
                    srv.load_snapshot(cl.snapshot(n, idx), set_fence=False,
                                      adopt_wal=True)
                    ph, pp = peers[pred]
                    pcl = ControlPlaneClient(ph, pp, 0, secret=secret,
                                             streams=1)
                    try:
                        srv.load_snapshot(pcl.snapshot(n, pred, rearm=True),
                                          set_fence=True)
                    finally:
                        pcl.close()
            finally:
                cl.close()
            logger.warning("shard %d: rejoin catch-up complete (snapshot "
                           "from shard %d)", idx, succ)
            return
        except (OSError, RuntimeError) as exc:
            last = exc
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"shard {idx}: rejoin catch-up failed within "
                    f"BLUEFOG_CP_REJOIN_TIMEOUT: {last}") from last
            time.sleep(0.2)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    max_mb = args.mailbox_max_mb
    if max_mb is None:
        max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
    cap = int(max_mb * (1 << 20))
    secret = os.environ.get("BLUEFOG_CP_SECRET", "")
    if args.rejoin and not args.port:
        print("shard_server: --rejoin requires an explicit --port (the "
              "routers hold the old endpoint)", file=sys.stderr)
        return 2
    # --rejoin arms the rejoin gate ATOMICALLY with the bind: any op
    # served against the not-yet-loaded store would lose records now and
    # resurrect them out of order later. The cap self-publish is skipped
    # in that case — a loopback put would park on the gate, and the
    # snapshot restores the key anyway.
    srv = ControlPlaneServer(args.world, args.port, secret=secret,
                             max_mailbox_bytes=cap,
                             rejoin_pending=args.rejoin)
    if not args.rejoin:
        # Self-publish the effective cap (value + 1 so 0 still means "not
        # published") through a loopback client; origins size deposit
        # pre-checks against the SERVING side's cap, and the attach-time
        # agreement check compares every shard's copy.
        try:
            cl = ControlPlaneClient("127.0.0.1", srv.port, 0, secret=secret,
                                    streams=1)
            cl.put("bf.cp.mailbox_cap_bytes", cap + 1)
            cl.close()
        except OSError as exc:  # serve anyway; attach falls back to knob
            logger.warning("shard %d: mailbox-cap self-publish failed (%s)",
                           args.shard, exc)

    peers = _parse_peers(args.peers) if args.peers else None
    if args.expect_peers:
        # two-phase: report the bound port, then wait for the full ring
        print(f"BF_SHARD_PORT {srv.port}", flush=True)
        line = sys.stdin.readline()
        if not line.startswith("BF_SHARD_PEERS"):
            print(f"shard_server: expected a BF_SHARD_PEERS line, got "
                  f"{line!r}", file=sys.stderr)
            srv.stop()
            return 2
        peers = _parse_peers(line.split(None, 1)[1])
    if args.rejoin and not (
            peers and len(peers) > 1
            and int(knob_env("BLUEFOG_CP_REPLICATION"))):
        print("shard_server: --rejoin requires a peer ring with "
              "BLUEFOG_CP_REPLICATION enabled (the gate would never "
              "open)", file=sys.stderr)
        srv.stop()
        return 2
    if peers and len(peers) > 1 and int(knob_env("BLUEFOG_CP_REPLICATION")):
        if args.rejoin:
            _rejoin_catch_up(srv, args.shard, peers, secret)
        sh, sp = peers[(args.shard + 1) % len(peers)]
        srv.set_successor(sh, sp, len(peers), args.shard)
        logger.info("shard %d: WAL replication to ring successor %s:%d",
                    args.shard, sh, sp)
        if args.rejoin:
            # Announce alive ONLY NOW — after our own WAL stream is armed.
            # Routers flip traffic back the moment they see the even
            # generation, and an op served before set_successor would be
            # acked UNREPLICATED (a split-brain seed the soak caught as
            # counter-era violations). Monotone put_max + the successor's
            # WAL propagate the flag to every shard.
            try:
                sh0, sp0 = peers[(args.shard + 1) % len(peers)]
                cl = ControlPlaneClient(sh0, sp0, 0, secret=secret,
                                        streams=1)
                flag = f"bf.cp.shard_dead.{args.shard}"
                cur = cl.put_max(flag, 0)
                if cur % 2 == 1:
                    cl.put_max(flag, cur + 1)
                cl.close()
            except OSError as exc:
                logger.warning("shard %d: alive-generation publish failed "
                               "(%s); routers will not re-route until an "
                               "operator republishes it", args.shard, exc)

    print(f"{READY_MARKER} {srv.port}", flush=True)
    logger.info("control-plane shard %d serving on port %d (world %d, "
                "mailbox cap %d bytes)", args.shard, srv.port, args.world,
                cap)

    done = threading.Event()
    if peers and len(peers) > 1 and int(knob_env("BLUEFOG_CP_REPLICATION")):
        # Alive keeper: a router whose redirect-verify dial loses a race
        # under a connect storm can FALSELY publish an odd (dead)
        # liveness generation for this perfectly live shard — and nothing
        # else would ever re-even it (the rejoin publish is one-shot).
        # While this process lives, it periodically re-asserts the next
        # even generation through its ring successor (whose WAL chains
        # the monotone put_max around the ring), so a false death claim
        # self-corrects within a poll interval; a real death stops the
        # keeper with the process.
        sh, sp = peers[(args.shard + 1) % len(peers)]
        flag = f"bf.cp.shard_dead.{args.shard}"

        def _alive_keeper() -> None:
            cl = None
            while not done.wait(2.0):
                try:
                    if cl is None:
                        cl = ControlPlaneClient(sh, sp, 0, secret=secret,
                                                streams=1)
                    cur = cl.put_max(flag, 0)
                    if cur % 2 == 1:
                        cl.put_max(flag, cur + 1)
                        logger.warning(
                            "shard %d: re-asserted ALIVE (liveness "
                            "generation %d -> %d; a peer's death claim "
                            "was spurious)", args.shard, cur, cur + 1)
                except OSError:
                    if cl is not None:
                        cl.close()
                    cl = None  # successor briefly away; redial next tick
            if cl is not None:
                cl.close()

        # bfcheck: ok-daemon-no-join (keeper must die WITH the process —
        # its whole job is that a real death stops the re-assertions; the
        # `done` event stops it on graceful SIGTERM teardown)
        threading.Thread(target=_alive_keeper, daemon=True,
                         name="bf-shard-alive").start()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
