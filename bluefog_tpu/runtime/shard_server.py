"""One control-plane shard server, as a standalone OS process.

The sharded control plane (docs/fault_tolerance.md, "Control-plane
sharding & failover") runs N of these; clients route keys across them with
:class:`bluefog_tpu.runtime.router.ShardRouter`. Launched by
``bfrun --cp-shards N``, by ``scripts/cp_soak.py``, and by the chaos tests
(which SIGKILL it mid-job on purpose):

    python bluefog_tpu/runtime/shard_server.py --port P --world W [--shard I]

Run BY FILE PATH it bootstraps lean — the relative imports below resolve
without executing ``bluefog_tpu/__init__`` (which imports jax): a shard
server must start in milliseconds, hold no accelerator state, and cost a
few MB of RSS, because the churn soak starts and kills them in bulk.
Importable normally (``bluefog_tpu.runtime.shard_server``) for in-process
use.

Prints ``BF_SHARD_READY <port>`` on stdout once serving (the spawn-side
readiness handshake), then blocks until SIGTERM/SIGINT. The job secret
rides ``BLUEFOG_CP_SECRET`` exactly as for the single-server plane, and
the server self-publishes its effective mailbox cap under
``bf.cp.mailbox_cap_bytes`` so attach-time agreement checks can reject a
mixed-cap cluster loudly (every shard must publish its OWN value — a
router must never write this key, or a mismatch would be masked).

Durable-plane peer wiring (r16): with ``--expect-peers`` the handshake is
two-phase — the server prints ``BF_SHARD_PORT <port>`` first, the spawner
collects every shard's port and writes one ``BF_SHARD_PEERS
host:port,host:port,...`` line to each shard's stdin, and only then does
the server configure its ring successor (WAL replication,
``BLUEFOG_CP_REPLICATION``) and print the READY line. Ephemeral ports
(``--port 0``) therefore need no pre-agreed port plan. ``--rejoin``
additionally pulls a state snapshot from the ring successor, loads it,
publishes the next EVEN liveness generation under ``bf.cp.shard_dead.<i>``
so every router moves the keyspace back, and publishes its CURRENT
endpoint under ``bf.cp.shard_addr.<i>`` (generation-stamped put_max) so a
rejoin on a NEW host:port (``--port 0`` included) is re-dialed too — the
r16 "must reuse its old endpoint" limit is lifted for the router plane.
(The ring PREDECESSOR's WAL successor stream is still pinned to the old
endpoint — ``set_successor`` is one-shot native-side — so replication to
a moved shard stays degraded until the ring is restarted; routed traffic
and catch-up are unaffected.)
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__" and __package__ in (None, ""):
    # Lean bootstrap: register dummy parent packages so the relative
    # imports below resolve WITHOUT executing bluefog_tpu/__init__ (jax)
    # or bluefog_tpu/runtime/__init__ (state -> jax).
    import types

    _here = os.path.dirname(os.path.abspath(__file__))
    _pkg = os.path.dirname(_here)
    # replace sys.path[0] (this script's directory — it would shadow the
    # stdlib `logging` with runtime/logging.py) with the repo root
    sys.path[0] = os.path.dirname(_pkg)
    for _name, _path in (("bluefog_tpu", _pkg),
                         ("bluefog_tpu.runtime", _here)):
        if _name not in sys.modules:
            _mod = types.ModuleType(_name)
            _mod.__path__ = [_path]
            sys.modules[_name] = _mod
    __package__ = "bluefog_tpu.runtime"

import argparse
import signal
import threading
import time

from .config import knob_env
from .logging import logger
from .native import ControlPlaneClient, ControlPlaneServer

READY_MARKER = "BF_SHARD_READY"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bf-shard-server",
        description="Serve one shard of the bluefog control plane.")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port to bind (0 = ephemeral, reported on the "
                        "READY line)")
    p.add_argument("--world", type=int, default=1,
                   help="number of controller processes in the job "
                        "(barrier arity; must match every shard)")
    p.add_argument("--shard", type=int, default=0,
                   help="this shard's index (logging only; routing is "
                        "decided client-side by key hash)")
    p.add_argument("--mailbox-max-mb", type=float, default=None,
                   help="per-mailbox byte cap (default: the "
                        "BLUEFOG_CP_MAILBOX_MAX_MB registry knob)")
    p.add_argument("--expect-peers", action="store_true",
                   help="two-phase start: print BF_SHARD_PORT, read one "
                        "'BF_SHARD_PEERS host:port,...' line from stdin, "
                        "wire the ring successor (WAL replication), then "
                        "print the READY line")
    p.add_argument("--peers", default=None, metavar="HOST:PORT,...",
                   help="explicit ring endpoint list (all shards, in "
                        "index order) when ports are known up front; "
                        "alternative to --expect-peers")
    p.add_argument("--rejoin", action="store_true",
                   help="restarted-shard catch-up: pull a state snapshot "
                        "from the ring successor, load it, and publish "
                        "the next even liveness generation plus this "
                        "server's current endpoint (bf.cp.shard_addr.<i>) "
                        "before READY (requires a peer list; a new port — "
                        "--port 0 included — is fine, routers re-dial it)")
    p.add_argument("--advertise-host", default=None,
                   help="host routers should re-dial after a rejoin "
                        "(default: this shard's entry in the peer list)")
    return p


def _parse_peers(spec: str):
    out = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port = item.rpartition(":")
        out.append((host, int(port)))
    return out


def _successor_count(nshards: int) -> int:
    """How many ring successors this shard streams its WAL to.

    ``BLUEFOG_CP_REPLICATION`` counts COPIES: 0 disables replication,
    1 is the legacy on-switch (aliases the r16 two-copy chain), R >= 2
    keeps R copies of every record — the primary plus min(R, nshards)-1
    successor streams. R=2 therefore stays byte-identical to the r16
    wire; R >= 3 arms quorum mode (commit = ack-from-majority)."""
    r = int(knob_env("BLUEFOG_CP_REPLICATION"))
    if r <= 0 or nshards < 2:
        return 0
    copies = 2 if r == 1 else min(r, nshards)
    return copies - 1


def _arm_partition_from_env(peers, shard_idx: int) -> None:
    """Arm the deterministic partition injector from ``BLUEFOG_CP_FAULT``
    (``partition=0,1|2,3[,part_after=S][,heal_after=S]``). The grammar
    names SHARD INDICES; only here — where the peer list pins each index
    to a port — can they resolve to the port->group map the native cut
    enforces at the client socket layer. The server's own replicator and
    rejoin clients inherit this side of the cut, so a minority shard
    loses its commit quorum exactly as a real switch split would."""
    from . import native as _native

    try:
        spec = _native.parse_fault_spec(
            os.environ.get("BLUEFOG_CP_FAULT", ""))
    except ValueError as exc:
        logger.warning("shard %d: bad BLUEFOG_CP_FAULT partition spec "
                       "(%s); injector not armed", shard_idx, exc)
        return
    groups = spec.get("partition")
    if not groups:
        return
    port_groups = {}
    self_group = -1
    for g, members in enumerate(groups):
        for m in members:
            if 0 <= m < len(peers):
                port_groups[peers[m][1]] = g
            if m == shard_idx:
                self_group = g
    _native.partition_arm(port_groups, self_group,
                          start_after=float(spec.get("part_after", 0.0)),
                          heal_after=float(spec.get("heal_after", 0.0)))
    logger.warning("shard %d: partition injector armed (side %d of %s, "
                   "part_after=%.3gs heal_after=%.3gs)", shard_idx,
                   self_group, groups, spec.get("part_after", 0.0),
                   spec.get("heal_after", 0.0))


def _published_addr(peers, idx: int, secret: str, skip: int = -1):
    """Best-effort: shard ``idx``'s CURRENT endpoint per the replicated
    ``bf.cp.shard_addr.<idx>`` key (None when never moved / no peer
    reachable). Lets a rejoiner catch up from a ring peer that itself
    rejoined on a new port earlier. ``skip`` names the CALLING shard:
    a same-port rejoiner must never dial its own listed endpoint — the
    op would park on its own still-closed rejoin gate (deadlock)."""
    from .router import SHARD_ADDR_FMT, unpack_shard_addr

    best = 0
    for j, (h, p) in enumerate(peers):
        if j == idx or j == skip:
            continue
        try:
            cl = ControlPlaneClient(h, p, 0, secret=secret, streams=1)
            try:
                best = max(best,
                           int(cl.get(SHARD_ADDR_FMT.format(idx=idx))))
            finally:
                cl.close()
        except (OSError, RuntimeError):
            continue
    dec = unpack_shard_addr(best)
    return (dec[1], dec[2]) if dec else None


def _rejoin_catch_up(srv, idx: int, peers, secret: str,
                     nt: int = 1) -> None:
    """Restarted-shard catch-up, two pulls with distinct roles:

    1. From the ring SUCCESSOR — this shard's own keyspace, which the
       successor replicated and has been serving since the death. The
       load also RESUMES this shard's WAL numbering (``adopt_wal``) from
       the fence the successor holds against this shard's stream: a
       restart back at zero would leave every post-rejoin record at or
       below that stale fence — silently dropped-and-acked by the
       successor, i.e. lost on this shard's next death.
    2. From the ring PREDECESSOR — ITS keyspace (this shard's replica
       role). The pull carries the receiver flag (``rearm``): serving it
       re-arms the predecessor's degraded stream from that exact cut,
       and ``set_fence`` adopts the cut's fence so the resumed stream
       skips records already folded in — gap-free.

    For a two-shard ring both roles are the same endpoint, so one
    unfiltered receiver-flagged pull carries everything at a single cut
    (two filtered pulls would open a gap between their cuts).

    Quorum mode (``nt`` >= 2 successor streams) generalizes both roles:
    the own-keyspace pull works from ANY surviving replica — every live
    successor is probed and the copy whose resume fence is NEWEST wins
    (taking the max is the gap check: resuming below a survivor's fence
    would leave post-rejoin records silently dropped-and-acked there) —
    and the replica role covers each of the nt ring PREDECESSORS with
    its own receiver-flagged pull (per-source fences, per-source
    re-arm). Dead predecessors are skipped: their streams restart fresh
    when they themselves rejoin, and this shard's per-source fence
    dedups the overlap."""
    n = len(peers)
    succ = (idx + 1) % n
    pred = (idx - 1) % n
    deadline = time.monotonic() + float(knob_env("BLUEFOG_CP_REJOIN_TIMEOUT"))
    last = None
    # quorum-mode pulls identify this shard to the serving peer via the
    # frame rank -(100+idx): the peer picks the resume fence of THIS
    # shard's stream and re-arms exactly this receiver's target stream
    snap_rank = -(100 + idx) if nt >= 2 else 0

    def _dial_peer(j):
        h, p = _published_addr(peers, j, secret, skip=idx) or peers[j]
        return ControlPlaneClient(h, p, snap_rank, secret=secret, streams=1)

    while True:
        try:
            if nt >= 2:
                import struct as _struct

                best_blob, best_resume, best_src = None, -1, -1
                for k in range(1, nt + 1):
                    s = (idx + k) % n
                    try:
                        cl = _dial_peer(s)
                    except (OSError, RuntimeError):
                        continue
                    try:
                        blob = cl.snapshot(n, idx)
                    finally:
                        cl.close()
                    if len(blob) < 16:
                        continue
                    resume = _struct.unpack("<Q", blob[8:16])[0]
                    if resume > best_resume or best_blob is None:
                        best_blob, best_resume, best_src = blob, resume, s
                if best_blob is None:
                    raise OSError(
                        f"no surviving replica of shard {idx}'s keyspace "
                        f"answered (probed {nt} ring successors)")
                srv.load_snapshot(best_blob, set_fence=False,
                                  adopt_wal=True, src_idx=idx)
                rearmed = []
                for k in range(1, nt + 1):
                    p_idx = (idx - k) % n
                    if p_idx == idx:
                        continue
                    try:
                        pcl = _dial_peer(p_idx)
                    except (OSError, RuntimeError):
                        continue  # dead predecessor: see docstring
                    try:
                        srv.load_snapshot(
                            pcl.snapshot(n, p_idx, rearm=True),
                            set_fence=True, src_idx=p_idx)
                        rearmed.append(p_idx)
                    finally:
                        pcl.close()
                logger.warning(
                    "shard %d: quorum rejoin catch-up complete (own "
                    "keyspace from shard %d at fence %d; re-armed "
                    "predecessor streams %s)", idx, best_src, best_resume,
                    rearmed or "none")
                return
            # a ring peer may itself have moved in an earlier rejoin; its
            # published address supersedes the static peer list
            host, port = _published_addr(peers, succ, secret, skip=idx) \
                or peers[succ]
            cl = ControlPlaneClient(host, port, 0, secret=secret, streams=1)
            try:
                if n <= 2:
                    # successor == predecessor: one cut carries both the
                    # served keyspace and the replica keyspace; the fence,
                    # the WAL resume, and the stream re-arm all anchor to
                    # that single cut
                    srv.load_snapshot(cl.snapshot(rearm=True),
                                      set_fence=True, adopt_wal=True)
                else:
                    srv.load_snapshot(cl.snapshot(n, idx), set_fence=False,
                                      adopt_wal=True)
                    ph, pp = _published_addr(peers, pred, secret,
                                             skip=idx) or peers[pred]
                    pcl = ControlPlaneClient(ph, pp, 0, secret=secret,
                                             streams=1)
                    try:
                        srv.load_snapshot(pcl.snapshot(n, pred, rearm=True),
                                          set_fence=True)
                    finally:
                        pcl.close()
            finally:
                cl.close()
            logger.warning("shard %d: rejoin catch-up complete (snapshot "
                           "from shard %d)", idx, succ)
            return
        except (OSError, RuntimeError) as exc:
            last = exc
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"shard {idx}: rejoin catch-up failed within "
                    f"BLUEFOG_CP_REJOIN_TIMEOUT: {last}") from last
            time.sleep(0.2)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    max_mb = args.mailbox_max_mb
    if max_mb is None:
        max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
    cap = int(max_mb * (1 << 20))
    secret = os.environ.get("BLUEFOG_CP_SECRET", "")
    # --rejoin arms the rejoin gate ATOMICALLY with the bind: any op
    # served against the not-yet-loaded store would lose records now and
    # resurrect them out of order later. The cap self-publish is skipped
    # in that case — a loopback put would park on the gate, and the
    # snapshot restores the key anyway.
    srv = ControlPlaneServer(args.world, args.port, secret=secret,
                             max_mailbox_bytes=cap,
                             rejoin_pending=args.rejoin)
    if not args.rejoin:
        # Self-publish the effective cap (value + 1 so 0 still means "not
        # published") through a loopback client; origins size deposit
        # pre-checks against the SERVING side's cap, and the attach-time
        # agreement check compares every shard's copy.
        try:
            cl = ControlPlaneClient("127.0.0.1", srv.port, 0, secret=secret,
                                    streams=1)
            cl.put("bf.cp.mailbox_cap_bytes", cap + 1)
            cl.close()
        except OSError as exc:  # serve anyway; attach falls back to knob
            logger.warning("shard %d: mailbox-cap self-publish failed (%s)",
                           args.shard, exc)

    peers = _parse_peers(args.peers) if args.peers else None
    if args.expect_peers:
        # two-phase: report the bound port, then wait for the full ring
        print(f"BF_SHARD_PORT {srv.port}", flush=True)
        line = sys.stdin.readline()
        if not line.startswith("BF_SHARD_PEERS"):
            print(f"shard_server: expected a BF_SHARD_PEERS line, got "
                  f"{line!r}", file=sys.stderr)
            srv.stop()
            return 2
        peers = _parse_peers(line.split(None, 1)[1])
    nt = _successor_count(len(peers)) if peers else 0
    if args.rejoin and not (peers and len(peers) > 1 and nt):
        print("shard_server: --rejoin requires a peer ring with "
              "BLUEFOG_CP_REPLICATION enabled (the gate would never "
              "open)", file=sys.stderr)
        srv.stop()
        return 2
    if peers and len(peers) > 1:
        _arm_partition_from_env(peers, args.shard)
    addr_val = None
    if peers and nt:
        if args.rejoin:
            _rejoin_catch_up(srv, args.shard, peers, secret, nt)
        if nt >= 2:
            targets = []
            for k in range(1, nt + 1):
                s = (args.shard + k) % len(peers)
                th, tp = (_published_addr(peers, s, secret, skip=args.shard)
                          if args.rejoin else None) or peers[s]
                targets.append((s, th, tp))
            srv.set_successors(targets, len(peers), args.shard)
            sh, sp = targets[0][1], targets[0][2]
            logger.info("shard %d: quorum WAL replication to %d ring "
                        "successors %s (commit = %d acks)", args.shard, nt,
                        [f"{t[1]}:{t[2]}" for t in targets], (nt + 2) // 2)
        else:
            succ_idx = (args.shard + 1) % len(peers)
            sh, sp = (_published_addr(peers, succ_idx, secret,
                                      skip=args.shard)
                      if args.rejoin else None) or peers[succ_idx]
            srv.set_successor(sh, sp, len(peers), args.shard)
            logger.info("shard %d: WAL replication to ring successor %s:%d",
                        args.shard, sh, sp)
        if args.rejoin:
            # Announce alive ONLY NOW — after our own WAL stream is armed.
            # Routers flip traffic back the moment they see the even
            # generation, and an op served before set_successor would be
            # acked UNREPLICATED (a split-brain seed the soak caught as
            # counter-era violations). Monotone put_max + the successor's
            # WAL propagate the flag to every shard. The next even
            # generation also stamps bf.cp.shard_addr.<i> with THIS
            # server's endpoint — the key routers consult before the
            # rejoin re-dial, which is what lets a restart land on a new
            # host:port (--port 0 included).
            from .router import pack_shard_addr

            adv_host = args.advertise_host or \
                (peers[args.shard][0] if args.shard < len(peers)
                 else "127.0.0.1")
            try:
                cl = ControlPlaneClient(sh, sp, 0, secret=secret,
                                        streams=1)
                flag = f"bf.cp.shard_dead.{args.shard}"
                cur = cl.put_max(flag, 0)
                # odd (dead) -> next even; even -> next even AGAIN so the
                # generation stamped into the address key is strictly
                # fresher than any earlier rejoin's (put_max can then
                # never keep a stale endpoint)
                new_gen = cur + 1 if cur % 2 == 1 else cur + 2
                cl.put_max(flag, new_gen)
                addr_val = pack_shard_addr(new_gen, adv_host, srv.port)
                cl.put_max(f"bf.cp.shard_addr.{args.shard}", addr_val)
                cl.close()
            except OSError as exc:
                logger.warning("shard %d: alive-generation publish failed "
                               "(%s); routers will not re-route until an "
                               "operator republishes it", args.shard, exc)

    print(f"{READY_MARKER} {srv.port}", flush=True)
    logger.info("control-plane shard %d serving on port %d (world %d, "
                "mailbox cap %d bytes)", args.shard, srv.port, args.world,
                cap)

    done = threading.Event()
    if peers and nt:
        # Alive keeper: a router whose redirect-verify dial loses a race
        # under a connect storm can FALSELY publish an odd (dead)
        # liveness generation for this perfectly live shard — and nothing
        # else would ever re-even it (the rejoin publish is one-shot).
        # While this process lives, it periodically re-asserts the next
        # even generation through its ring successor (whose WAL chains
        # the monotone put_max around the ring), so a false death claim
        # self-corrects within a poll interval; a real death stops the
        # keeper with the process.
        #
        # Quorum mode adds two partition rules. (1) While this server is
        # below its commit quorum (minority side of a cut) it must NOT
        # re-even its own flag: the majority legitimately declared it
        # dead and failed its keyspace over — re-asserting alive from
        # the minority would split-brain the routing. (2) Once quorum is
        # restored, a flag still odd means routers served this shard's
        # keyspace elsewhere during the episode, so its local copy is
        # stale: a guarded IN-PLACE self-rejoin (reset_store + snapshot
        # catch-up from the surviving replicas, then reopening the gate)
        # rebuilds it exactly like a restarted process before the next
        # even generation announces it back.
        flag = f"bf.cp.shard_dead.{args.shard}"
        addr_key = f"bf.cp.shard_addr.{args.shard}"

        def _alive_keeper() -> None:
            from .router import pack_shard_addr

            cl = None
            saw_qlost = False
            adv_host = args.advertise_host or peers[args.shard][0]
            while not done.wait(2.0):
                try:
                    if nt >= 2 and \
                            (srv.stats() or {}).get("quorum_state") == 2:
                        saw_qlost = True
                        continue  # rule (1): never re-assert below quorum
                    if cl is None:
                        ah, ap = _published_addr(
                            peers, (args.shard + 1) % len(peers), secret,
                            skip=args.shard) \
                            or peers[(args.shard + 1) % len(peers)]
                        cl = ControlPlaneClient(ah, ap, 0, secret=secret,
                                                streams=1)
                    cur = cl.put_max(flag, 0)
                    if cur < 0:
                        # transport-level failure surfaces as -1, not an
                        # exception: the successor died (possibly to come
                        # back on a NEW port) — drop the client and
                        # re-resolve its published address next tick
                        cl.close()
                        cl = None
                        continue
                    if cur % 2 == 1:
                        if nt >= 2 and saw_qlost:
                            # rule (2): flagged dead during a real quorum
                            # loss — the keyspace moved; rebuild in place
                            # before announcing alive
                            try:
                                srv.reset_store()
                                _rejoin_catch_up(srv, args.shard, peers,
                                                 secret, nt)
                                srv.rejoin_done()
                            except (OSError, RuntimeError) as exc:
                                logger.error(
                                    "shard %d: post-partition self-rejoin "
                                    "failed (%s); staying flagged dead, "
                                    "retrying next tick", args.shard, exc)
                                continue
                            saw_qlost = False
                            logger.warning(
                                "shard %d: post-partition self-rejoin "
                                "complete (store rebuilt from surviving "
                                "replicas)", args.shard)
                        cl.put_max(flag, cur + 1)
                        if addr_val is not None or (nt >= 2 and cur > 0):
                            # a moved shard's endpoint must outlive false
                            # death claims: restamp it at the new even gen
                            cl.put_max(addr_key,
                                       pack_shard_addr(
                                           cur + 1, adv_host, srv.port))
                        logger.warning(
                            "shard %d: re-asserted ALIVE (liveness "
                            "generation %d -> %d; a peer's death claim "
                            "was spurious)", args.shard, cur, cur + 1)
                    else:
                        # quorum (if it was lost) is held again and no
                        # router flagged us dead: streams were only
                        # suspect-parked across the cut and resume
                        # gap-free — no rebuild needed
                        saw_qlost = False
                        if addr_val is not None:
                            cl.put_max(addr_key, addr_val)
                except OSError:
                    if cl is not None:
                        cl.close()
                    cl = None  # successor briefly away; redial next tick
            if cl is not None:
                cl.close()

        # bfcheck: ok-daemon-no-join (keeper must die WITH the process —
        # its whole job is that a real death stops the re-assertions; the
        # `done` event stops it on graceful SIGTERM teardown)
        threading.Thread(target=_alive_keeper, daemon=True,
                         name="bf-shard-alive").start()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
