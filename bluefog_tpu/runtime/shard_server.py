"""One control-plane shard server, as a standalone OS process.

The sharded control plane (docs/fault_tolerance.md, "Control-plane
sharding & failover") runs N of these; clients route keys across them with
:class:`bluefog_tpu.runtime.router.ShardRouter`. Launched by
``bfrun --cp-shards N``, by ``scripts/cp_soak.py``, and by the chaos tests
(which SIGKILL it mid-job on purpose):

    python bluefog_tpu/runtime/shard_server.py --port P --world W [--shard I]

Run BY FILE PATH it bootstraps lean — the relative imports below resolve
without executing ``bluefog_tpu/__init__`` (which imports jax): a shard
server must start in milliseconds, hold no accelerator state, and cost a
few MB of RSS, because the churn soak starts and kills them in bulk.
Importable normally (``bluefog_tpu.runtime.shard_server``) for in-process
use.

Prints ``BF_SHARD_READY <port>`` on stdout once serving (the spawn-side
readiness handshake), then blocks until SIGTERM/SIGINT. The job secret
rides ``BLUEFOG_CP_SECRET`` exactly as for the single-server plane, and
the server self-publishes its effective mailbox cap under
``bf.cp.mailbox_cap_bytes`` so attach-time agreement checks can reject a
mixed-cap cluster loudly (every shard must publish its OWN value — a
router must never write this key, or a mismatch would be masked).
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__" and __package__ in (None, ""):
    # Lean bootstrap: register dummy parent packages so the relative
    # imports below resolve WITHOUT executing bluefog_tpu/__init__ (jax)
    # or bluefog_tpu/runtime/__init__ (state -> jax).
    import types

    _here = os.path.dirname(os.path.abspath(__file__))
    _pkg = os.path.dirname(_here)
    # replace sys.path[0] (this script's directory — it would shadow the
    # stdlib `logging` with runtime/logging.py) with the repo root
    sys.path[0] = os.path.dirname(_pkg)
    for _name, _path in (("bluefog_tpu", _pkg),
                         ("bluefog_tpu.runtime", _here)):
        if _name not in sys.modules:
            _mod = types.ModuleType(_name)
            _mod.__path__ = [_path]
            sys.modules[_name] = _mod
    __package__ = "bluefog_tpu.runtime"

import argparse
import signal
import threading

from .config import knob_env
from .logging import logger
from .native import ControlPlaneClient, ControlPlaneServer

READY_MARKER = "BF_SHARD_READY"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bf-shard-server",
        description="Serve one shard of the bluefog control plane.")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port to bind (0 = ephemeral, reported on the "
                        "READY line)")
    p.add_argument("--world", type=int, default=1,
                   help="number of controller processes in the job "
                        "(barrier arity; must match every shard)")
    p.add_argument("--shard", type=int, default=0,
                   help="this shard's index (logging only; routing is "
                        "decided client-side by key hash)")
    p.add_argument("--mailbox-max-mb", type=float, default=None,
                   help="per-mailbox byte cap (default: the "
                        "BLUEFOG_CP_MAILBOX_MAX_MB registry knob)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    max_mb = args.mailbox_max_mb
    if max_mb is None:
        max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
    cap = int(max_mb * (1 << 20))
    secret = os.environ.get("BLUEFOG_CP_SECRET", "")
    srv = ControlPlaneServer(args.world, args.port, secret=secret,
                             max_mailbox_bytes=cap)
    # Self-publish the effective cap (value + 1 so 0 still means "not
    # published") through a loopback client; origins size deposit
    # pre-checks against the SERVING side's cap, and the attach-time
    # agreement check compares every shard's copy.
    try:
        cl = ControlPlaneClient("127.0.0.1", srv.port, 0, secret=secret,
                                streams=1)
        cl.put("bf.cp.mailbox_cap_bytes", cap + 1)
        cl.close()
    except OSError as exc:  # serve anyway; attach falls back to its knob
        logger.warning("shard %d: mailbox-cap self-publish failed (%s)",
                       args.shard, exc)

    print(f"{READY_MARKER} {srv.port}", flush=True)
    logger.info("control-plane shard %d serving on port %d (world %d, "
                "mailbox cap %d bytes)", args.shard, srv.port, args.world,
                cap)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
