"""Always-on flight recorder: a black box for postmortem dumps.

The timeline (``BLUEFOG_TIMELINE``, runtime/timeline.py) answers "show me
everything" — opt-in, file-backed, heavy. This module answers the question
production systems actually face: *the job just died / wedged / lost a
peer — what were the last few thousand things it did?* It keeps a
fixed-capacity in-memory ring of spans / instants / counters / flow events
using the r10 hot-path discipline (slotted writes into preallocated numpy
columns, no per-event object retention; the per-record cost is
microbench-asserted by ``make flight-smoke``), and dumps it — merged with
the metrics registry snapshot and the native transport's own event ring —
when something goes wrong:

  * a fatal exception escaping an optimizer step (``PeerLostError``
    included),
  * a stall detected by the watchdog,
  * an uncaught exception unwinding the process (excepthook chain — the
    abnormal-exit path),
  * an explicit ``bf.flight_dump()``,
  * a **cluster-wide remote trigger**: ``bfrun --dump`` bumps a KV flag
    that every rank's heartbeat/watchdog tick polls; each rank dumps
    locally AND publishes a packed tail under ``bf.flight.<rank>``, so an
    operator without filesystem access to any worker still gets a merged,
    clock-synced, cross-rank snapshot.

Every dump carries a wall-clock anchor (the r10 ``bf.clock_sync_us``
discipline), so :func:`chrome_events` converts it to a chrome-tracing
fragment on the shared wall-clock axis — per-rank dumps merge exactly like
timeline files do (scripts/merge_timelines.py), deposit→drain flow arrows
included.

Recording is ALWAYS on (``BLUEFOG_FLIGHT_DISABLE=1`` opts out); only
dumping does I/O. A torn or lost record under a cross-thread race is an
acceptable telemetry error, same trade as the metrics registry.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import traceback
import zlib
from typing import Dict, List, Optional

import numpy as np

from .config import knob_env
from .logging import logger

# -- event kinds -------------------------------------------------------------

SPAN_B = 1    # span begin              (a = arg, b = aux)
SPAN_E = 2    # span end                (a = arg, b = aux)
INSTANT = 3   # point event             (a = arg, b = aux)
COUNTER = 4   # counter sample          (a = value)
FLOW_S = 5    # flow start (deposit)    (a = bytes, b = flow id)
FLOW_F = 6    # flow finish (drain)     (a = bytes, b = flow id)

_KIND_NAMES = {SPAN_B: "B", SPAN_E: "E", INSTANT: "i", COUNTER: "C",
               FLOW_S: "s", FLOW_F: "f"}

# KV keys for the cluster-wide remote trigger (bfrun --dump)
TRIGGER_KEY = "bf.flight.trigger"
ACK_KEY_FMT = "bf.flight.ack.{rank}"
DATA_KEY_FMT = "bf.flight.{rank}"

_PACK_MAGIC = b"BFF1"


class FlightRecorder:
    """Fixed-capacity ring of recent events.

    The hot path (:meth:`rec`) is five slotted stores into preallocated
    numpy columns plus one ``perf_counter_ns`` — no lock, no per-event
    Python object kept. Name interning (:meth:`intern`) is the only
    allocating operation and only allocates the FIRST time a name is seen;
    hot call sites cache the id.
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        if capacity is None:
            capacity = int(knob_env("BLUEFOG_FLIGHT_CAPACITY"))
        cap = 1
        while cap < max(256, capacity):
            cap <<= 1
        self._mask = cap - 1
        self._kind = np.zeros(cap, np.int64)
        self._name = np.zeros(cap, np.int64)
        self._t = np.zeros(cap, np.int64)      # perf_counter_ns
        self._a = np.zeros(cap, np.float64)
        self._b = np.zeros(cap, np.int64)
        self._n = 0
        self._names: List[str] = []
        self._ids: Dict[str, int] = {}
        self._mu = threading.Lock()  # interning only — never the hot path
        # Clock-sync anchor (r10 discipline): wall-clock microseconds
        # captured against the same perf_counter origin the ring records,
        # so dumps from different processes land on one wall-clock axis.
        self._anchor_perf_ns = time.perf_counter_ns()
        self._anchor_wall_us = time.time_ns() // 1000

    @property
    def capacity(self) -> int:
        return self._mask + 1

    # -- producer side (any thread; a rare lost record is acceptable) ------

    def intern(self, name: str) -> int:
        i = self._ids.get(name)
        if i is None:
            with self._mu:
                i = self._ids.get(name)
                if i is None:
                    i = len(self._names)
                    self._names.append(name)
                    self._ids[name] = i
        return i

    def rec(self, kind: int, name_id: int, a: float = 0.0,
            b: int = 0) -> None:
        i = self._n & self._mask
        self._t[i] = time.perf_counter_ns()
        self._kind[i] = kind
        self._name[i] = name_id
        self._a[i] = a
        self._b[i] = b
        self._n += 1

    # conveniences (intern per call — fine off the hot path)

    def begin(self, name: str, a: float = 0.0, b: int = 0) -> None:
        self.rec(SPAN_B, self.intern(name), a, b)

    def end(self, name: str, a: float = 0.0, b: int = 0) -> None:
        self.rec(SPAN_E, self.intern(name), a, b)

    def instant(self, name: str, a: float = 0.0, b: int = 0) -> None:
        self.rec(INSTANT, self.intern(name), a, b)

    def counter(self, name: str, value: float) -> None:
        self.rec(COUNTER, self.intern(name), value)

    @contextlib.contextmanager
    def span(self, name: str, a: float = 0.0, b: int = 0):
        nid = self.intern(name)
        self.rec(SPAN_B, nid, a, b)
        try:
            yield
        finally:
            self.rec(SPAN_E, nid, a, b)

    # -- snapshot ----------------------------------------------------------

    def _wall_us(self, t_perf_ns) -> float:
        return self._anchor_wall_us + (t_perf_ns - self._anchor_perf_ns) / 1e3

    def snapshot(self) -> dict:
        """Decode the ring oldest→newest into plain lists.

        A writer racing the snapshot can tear the very newest slots; for a
        postmortem tail that is irrelevant (and a dump normally runs after
        the interesting events, not during them)."""
        n = self._n
        cap = self._mask + 1
        count = min(n, cap)
        start = n - count
        idx = (start + np.arange(count)) & self._mask
        events = {
            "kind": self._kind[idx].tolist(),
            "name": self._name[idx].tolist(),
            "t_wall_us": [float(self._wall_us(int(t)))
                          for t in self._t[idx]],
            "a": self._a[idx].tolist(),
            "b": self._b[idx].tolist(),
        }
        return {
            "schema": 1,
            "anchor": {"wall_us": self._anchor_wall_us},
            "recorded": n,
            "dropped": max(0, n - cap),
            "names": list(self._names),
            "events": events,
        }


class _NullRecorder:
    """Recording disabled (BLUEFOG_FLIGHT_DISABLE=1): every entry point is
    an attribute-lookup no-op so call sites never branch."""

    capacity = 0

    def intern(self, name: str) -> int:
        return 0

    def rec(self, *a, **k) -> None:
        pass

    begin = end = instant = counter = rec

    @contextlib.contextmanager
    def span(self, *a, **k):
        yield

    def snapshot(self) -> dict:
        return {"schema": 1, "anchor": {"wall_us": time.time_ns() // 1000},
                "recorded": 0, "dropped": 0, "names": [],
                "events": {"kind": [], "name": [], "t_wall_us": [], "a": [],
                           "b": []}}


_rec_mu = threading.Lock()
_recorder = None


def recorder():
    """The process-global recorder (created on first use; always on unless
    ``BLUEFOG_FLIGHT_DISABLE=1``)."""
    global _recorder
    r = _recorder
    if r is None:
        with _rec_mu:
            if _recorder is None:
                _recorder = (_NullRecorder()
                             if knob_env("BLUEFOG_FLIGHT_DISABLE")
                             else FlightRecorder())
            r = _recorder
    return r


def reset_for_job() -> None:
    """Fresh ring + clock anchor for a new ``bf.init`` (the previous job's
    tail is gone — a dump belongs to the job that crashed, not its
    predecessor). Re-reads the disable/capacity knobs."""
    global _recorder, _last_dump, _last_trigger
    with _rec_mu:
        _recorder = (_NullRecorder() if knob_env("BLUEFOG_FLIGHT_DISABLE")
                     else FlightRecorder())
    _last_dump = 0.0
    _last_trigger = None


# -- dumping -----------------------------------------------------------------

_last_dump = 0.0
_dump_mu = threading.Lock()


def _dump_dir() -> str:
    return knob_env("BLUEFOG_FLIGHT_DIR") or "."


def _identity():
    from . import control_plane as _cp
    from .state import _global_state

    st = _global_state()
    rank = st.process_index if st.initialized else 0
    world = st.process_count if st.initialized else 1
    try:
        inc = _cp.incarnation()
    except Exception:  # noqa: BLE001 — identity is best-effort in a dump
        inc = 0
    return rank, world, inc


def build_dump(reason: str, exc: Optional[BaseException] = None) -> dict:
    """Assemble the full dump document: ring tail + native transport ring
    + metrics snapshot + identity. Never raises."""
    rank, world, inc = _identity()
    doc = {
        "schema": 1,
        "meta": {
            "reason": reason,
            "rank": rank,
            "world": world,
            "inc": inc,
            "pid": os.getpid(),
            "ts": time.time(),
            "exception": None if exc is None else "".join(
                traceback.format_exception_only(type(exc), exc)).strip(),
        },
    }
    doc.update(recorder().snapshot())
    try:
        from . import native as _native

        doc["native"] = _native.flight_events()
    except Exception as e:  # noqa: BLE001 — a dump must always produce
        doc["native"] = []
        logger.debug("flight: native ring unavailable (%s)", e)
    try:
        from . import metrics as _metrics

        doc["metrics"] = _metrics.snapshot()
    except Exception as e:  # noqa: BLE001
        doc["metrics"] = {}
        logger.debug("flight: metrics snapshot failed (%s)", e)
    return doc


def pack_dump(doc: dict) -> bytes:
    """Wire form for the KV tail (``bf.flight.<rank>``): magic + zlib'd
    JSON — readable from an external process without importing jax."""
    return _PACK_MAGIC + zlib.compress(
        json.dumps(doc).encode(), level=6)


def unpack_dump(blob: bytes) -> dict:
    if len(blob) < 4 or blob[:4] != _PACK_MAGIC:
        raise ValueError("not a packed flight dump (bad magic)")
    return json.loads(zlib.decompress(blob[4:]).decode())


def dump(reason: str = "explicit", exc: Optional[BaseException] = None,
         path: Optional[str] = None, publish: bool = True,
         force: bool = True, cl=None) -> Optional[str]:
    """Write the flight dump locally and (best-effort) publish the packed
    tail to the control-plane KV. Returns the local path, or None when
    rate-limited / both sinks failed. Never raises.

    ``force=False`` applies the automatic-trigger rate limit
    (``BLUEFOG_FLIGHT_MIN_INTERVAL``) so a PeerLostError storm or a
    wedged-handle sweep cannot spam dumps; explicit/remote dumps bypass it.
    """
    global _last_dump
    now = time.monotonic()
    with _dump_mu:
        if not force:
            min_gap = float(knob_env("BLUEFOG_FLIGHT_MIN_INTERVAL"))
            if _last_dump and now - _last_dump < min_gap:
                return None
        _last_dump = now
    doc = build_dump(reason, exc)
    rank = doc["meta"]["rank"]
    out_path: Optional[str] = None
    if path is None:
        path = os.path.join(_dump_dir(), f"bf_flight_{rank}.json")
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        out_path = path
        logger.error("flight recorder dump (%s) -> %s", reason, path)
    except OSError as e:
        logger.error("flight: local dump to %s failed (%s)", path, e)
    if publish:
        try:
            if cl is None:
                from . import control_plane as _cp

                cl = _cp.client() if _cp.active() else None
            if cl is not None:
                cl.put_bytes(DATA_KEY_FMT.format(rank=rank),
                             pack_dump(doc))
        except Exception as e:  # noqa: BLE001 — best effort by design
            logger.debug("flight: KV tail publish failed (%s)", e)
    return out_path


def fatal(where: str, exc: BaseException) -> Optional[str]:
    """Record a fatal instant and dump (rate-limited). The instant lands
    in the ring BEFORE the snapshot, so the dump's own tail contains the
    failure marker the merged view is searched for."""
    r = recorder()
    r.instant(f"fatal.{where}")
    return dump(reason=f"{where}: {type(exc).__name__}", exc=exc,
                force=False)


# -- abnormal-exit hook ------------------------------------------------------

_hook_installed = False


def install_excepthook() -> None:
    """Chain ``sys.excepthook`` so an uncaught exception unwinding the
    process leaves a dump behind (the atexit-on-abnormal-exit path: atexit
    itself cannot see why the interpreter is exiting, the hook can).
    Idempotent."""
    global _hook_installed
    if _hook_installed:
        return
    _hook_installed = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            fatal("uncaught", exc if exc is not None else exc_type())
        except Exception:  # noqa: BLE001 — never mask the real traceback
            pass
        prev(exc_type, exc, tb)

    sys.excepthook = _hook


# -- cluster-wide remote trigger ---------------------------------------------

_last_trigger: Optional[int] = None


def latch_trigger(cl) -> None:
    """Record the CURRENT trigger value as already-handled (called by
    ``bf.init`` right after the control plane attaches). A rank joining
    after an old trigger must not replay it — but everything bumped after
    this point fires, closing the race where an operator's ``bfrun
    --dump`` lands between init and the first poll tick (which a lazy
    first-poll latch would silently swallow)."""
    global _last_trigger
    try:
        _last_trigger = int(cl.get(TRIGGER_KEY))
    except Exception:  # noqa: BLE001 — init must not fail on telemetry
        _last_trigger = 0


def poll_remote_trigger(cl) -> bool:
    """One poll of the ``bfrun --dump`` KV flag (called from the heartbeat
    tick and the watchdog cycle). Returns True when a dump fired."""
    global _last_trigger
    try:
        val = int(cl.get(TRIGGER_KEY))
    except Exception:  # noqa: BLE001 — observability threads never raise
        return False
    if _last_trigger is None:
        # no eager latch ran (no bf.init on this path): latch defensively
        _last_trigger = val
        return False
    if val <= _last_trigger:
        return False
    _last_trigger = val
    rank, _, _ = _identity()
    dump(reason=f"remote-trigger #{val}", publish=True, force=True, cl=cl)
    try:
        cl.put(ACK_KEY_FMT.format(rank=rank), val)
    except Exception as e:  # noqa: BLE001
        logger.debug("flight: trigger ack failed (%s)", e)
    return True


# -- chrome-tracing conversion + cross-rank merge ----------------------------

def chrome_events(doc: dict) -> list:
    """Convert one dump to chrome-tracing events on the WALL-CLOCK axis
    (timestamps are already wall microseconds, so per-rank fragments
    overlay directly; a leading ``bf.clock_sync_us`` counter keeps the
    result merge-compatible with timeline files)."""
    pid = doc.get("meta", {}).get("rank", 0)
    names = doc.get("names", [])
    ev = doc.get("events", {})
    out: list = []
    ts0 = None
    for kind, nid, ts, a, b in zip(ev.get("kind", []), ev.get("name", []),
                                   ev.get("t_wall_us", []), ev.get("a", []),
                                   ev.get("b", [])):
        if ts0 is None:
            ts0 = ts
            out.append({"name": "bf.clock_sync_us", "cat": "bf", "ph": "C",
                        "ts": ts, "pid": pid, "tid": 0,
                        "args": {"value": ts}})
        name = names[nid] if 0 <= nid < len(names) else f"?{nid}"
        ph = _KIND_NAMES.get(kind)
        if ph is None:
            continue
        e = {"name": name, "cat": "bf.flight", "ph": ph, "ts": ts,
             "pid": pid, "tid": 0}
        if ph == "B" or ph == "E":
            e["args"] = {"a": a, "b": b}
        elif ph == "i":
            e["s"] = "t"
            e["args"] = {"a": a, "b": b}
        elif ph == "C":
            e["args"] = {"value": a}
        else:  # flow s/f — id binds deposit to drain across ranks
            e["cat"] = "bf.flow"
            e["id"] = int(b)
            e["args"] = {"bytes": a}
            if ph == "f":
                e["bp"] = "e"
        out.append(e)
    # native transport ring: instants on a dedicated lane
    for t_us, kind, a, b in doc.get("native", []):
        out.append({"name": f"native.{_NATIVE_KINDS.get(kind, kind)}",
                    "cat": "bf.native", "ph": "i", "s": "t", "ts": t_us,
                    "pid": pid, "tid": 999, "args": {"a": a, "b": b}})
    return out


# native flight-ring kinds (mirror of csrc/bf_runtime.cc FlightRec callers)
_NATIVE_KINDS = {1: "redial_attempt", 2: "redial", 3: "stale_frame",
                 4: "stripe", 5: "striped_xfer", 6: "shard_failover"}


def merge_dumps(docs: List[dict]) -> list:
    """Merge per-rank dumps into one chrome trace (earliest event at
    ts=0), the ``bfrun --dump`` output an operator loads into Perfetto."""
    events: list = []
    pids = set()
    for doc in docs:
        events.extend(chrome_events(doc))
        pids.add(doc.get("meta", {}).get("rank", 0))
    if events:
        base = min(e["ts"] for e in events)
        for e in events:
            e["ts"] = e["ts"] - base
    events.sort(key=lambda e: e.get("ts", 0.0))
    for pid in sorted(pids):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"bluefog rank {pid}"}})
    return events


# -- step-time attribution ---------------------------------------------------

# span name -> phase bucket. win.fold nests inside win.drain on the drain
# side (the overlap is subtracted so buckets stay disjoint); win.publish
# and win.wire are the two socket legs of the put path.
_PHASE_OF = {
    "opt.local": "local",
    "opt.pack": "pack",
    "opt.unpack": "unpack",
    "win.wire": "wire",
    "win.publish": "wire",
    "win.drain": "drain",
    "win.fold": "fold",
    # the hybrid plane's fused compiled-partition program (ISSUE r13):
    # gossip time that moved OFF the wire/drain phases shows up here
    "win.compiled": "compiled",
}


def _overlap(iv_a, iv_b) -> float:
    """Total seconds of intervals in iv_a covered by intervals in iv_b."""
    total = 0.0
    for a0, a1 in iv_a:
        for b0, b1 in iv_b:
            lo, hi = max(a0, b0), min(a1, b1)
            if hi > lo:
                total += hi - lo
    return total


def _spans_in(doc_events, names, t0, t1):
    """Matched (begin, end) wall-us intervals for each span name, clipped
    to [t0, t1]; unmatched begins are ignored (the ring may have evicted
    the other edge)."""
    out: Dict[str, list] = {n: [] for n in names}
    open_at: Dict[str, list] = {}
    for kind, name, ts in doc_events:
        if name not in out:
            continue
        if kind == SPAN_B:
            open_at.setdefault(name, []).append(ts)
        elif kind == SPAN_E and open_at.get(name):
            b = open_at[name].pop()
            lo, hi = max(b, t0), min(ts, t1)
            if hi > lo:
                out[name].append((lo, hi))
    return out


def analyze_dump(doc: dict) -> Optional[dict]:
    """Per-step attribution over one dump: the last COMPLETE ``opt.step``
    span's phase breakdown plus per-edge deposit totals. Returns None when
    the ring holds no complete step."""
    names = doc.get("names", [])
    ev = doc.get("events", {})
    rows = [(k, names[n] if 0 <= n < len(names) else "?", t, a, b)
            for k, n, t, a, b in zip(ev.get("kind", []), ev.get("name", []),
                                     ev.get("t_wall_us", []),
                                     ev.get("a", []), ev.get("b", []))]
    # last complete step span
    step_b = step_e = None
    step_no = None
    for k, name, t, a, b in reversed(rows):
        if name != "opt.step":
            continue
        if k == SPAN_E and step_e is None:
            step_e, step_no = t, b
        elif k == SPAN_B and step_e is not None and t < step_e:
            step_b = t
            break
    if step_b is None or step_e is None:
        return None
    t0, t1 = step_b, step_e
    step_sec = (t1 - t0) / 1e6
    triples = [(k, name, t) for k, name, t, _, _ in rows]
    spans = _spans_in(triples, set(_PHASE_OF) | {"opt.gossip"}, t0, t1)
    phases = {p: 0.0 for p in
              ("local", "pack", "wire", "drain", "fold", "unpack",
               "compiled")}
    for name, ivs in spans.items():
        p = _PHASE_OF.get(name)
        if p:
            phases[p] += sum(hi - lo for lo, hi in ivs) / 1e6
    # fold spans nest inside the drain sweep (owner side) and inside the
    # get path's pull leg: carve the overlap out so buckets stay disjoint
    phases["drain"] -= _overlap(spans["win.drain"], spans["win.fold"]) / 1e6
    phases["wire"] -= _overlap(spans["win.wire"], spans["win.fold"]) / 1e6
    gossip_sec = sum(hi - lo for lo, hi in spans["opt.gossip"]) / 1e6
    attributed = sum(phases.values())
    other = max(0.0, step_sec - attributed)
    # per-edge deposit totals (flow starts) + per-origin drain totals
    edges: Dict[str, dict] = {}
    drains: Dict[str, dict] = {}
    for k, name, t, a, b in rows:
        if not t0 <= t <= t1:
            continue
        if k == FLOW_S and name.startswith("edge."):
            _, src, dst = name.split(".")
            e = edges.setdefault(f"{src}->{dst}",
                                 {"bytes": 0.0, "deposits": 0})
            e["bytes"] += a
            e["deposits"] += 1
        elif k == FLOW_F and name.startswith("drain."):
            d = drains.setdefault(name.split(".", 1)[1],
                                  {"bytes": 0.0, "deposits": 0})
            d["bytes"] += a
            d["deposits"] += 1
    # apportion the wire phase over edges by byte share (the put batch is
    # one pipelined call — per-edge wire time is a byte-weighted estimate,
    # exact per-stripe timings live in the native ring)
    total_edge_bytes = sum(e["bytes"] for e in edges.values())
    for e in edges.values():
        share = e["bytes"] / total_edge_bytes if total_edge_bytes else 0.0
        e["wire_sec_est"] = phases["wire"] * share
    return {
        "step": int(step_no or 0),
        "step_sec": step_sec,
        "gossip_sec": gossip_sec,
        "phases": phases,
        "other_sec": other,
        "coverage": attributed / step_sec if step_sec else 0.0,
        "edges": edges,
        "drains": drains,
    }


def step_report() -> Optional[dict]:
    """``bf.step_report()``: attribution of the most recent complete
    optimizer step from the live ring (no dump file needed). None until a
    step completed."""
    return analyze_dump({"names": list(getattr(recorder(), "_names", [])),
                         "events": recorder().snapshot()["events"]})


# -- serve request-path attribution ------------------------------------------

# Span names the serving plane records when BLUEFOG_TRACE_SERVE is on.
# Request-scoped spans carry the 63-bit trace id in the ``b`` column so
# concurrent requests interleave freely in the ring and still match up.
SERVE_PHASES = ("admit", "queue", "swap_blocked", "linger", "decode",
                "reply")
_SERVE_PHASE_SPANS = {
    "serve.admit": "admit",
    "serve.queue": "queue",
    "serve.linger": "linger",
    "serve.decode": "decode",
}


def _pctile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * len(sorted_vals))) - 1))
    return float(sorted_vals[i])


def analyze_serve(doc: dict) -> Optional[dict]:
    """Per-request attribution over one dump: every COMPLETE ``serve.req``
    trace in the ring broken into disjoint phase buckets (admit, queue,
    swap_blocked, linger, decode, reply) plus p50/p99 aggregates per phase
    and per pull endpoint. Returns None when the ring holds no complete
    request trace.

    ``swap_blocked`` is derived — the overlap of a trace's queue wait with
    the poller's ``serve.pull`` spans, carved out of ``queue`` so buckets
    stay disjoint (the same discipline ``analyze_dump`` applies to
    ``win.fold``); ``reply`` is the tail between decode end and the
    request-span end. The ``serve.req`` end event's ``a`` column carries
    the snapshot version that answered, which is what lineage resolution
    keys on.
    """
    names = doc.get("names", [])
    ev = doc.get("events", {})
    rows = [(k, names[n] if 0 <= n < len(names) else "?", t, a, b)
            for k, n, t, a, b in zip(ev.get("kind", []), ev.get("name", []),
                                     ev.get("t_wall_us", []),
                                     ev.get("a", []), ev.get("b", []))]
    req_b: Dict[int, float] = {}
    req_e: Dict[int, tuple] = {}
    phase_open: Dict[tuple, float] = {}
    phase_iv: Dict[int, Dict[str, list]] = {}
    pulls: list = []
    pull_open: list = []
    ep_open: Dict[int, list] = {}
    ep_spans: Dict[int, list] = {}
    failovers = 0
    for k, name, t, a, b in rows:
        if name == "serve.req":
            if k == SPAN_B:
                req_b[int(b)] = t
            elif k == SPAN_E:
                req_e[int(b)] = (t, a)
        elif name in _SERVE_PHASE_SPANS:
            key = (name, int(b))
            if k == SPAN_B:
                phase_open[key] = t
            elif k == SPAN_E and key in phase_open:
                iv = phase_iv.setdefault(int(b), {})
                iv.setdefault(_SERVE_PHASE_SPANS[name], []).append(
                    (phase_open.pop(key), t))
        elif name == "serve.pull":
            if k == SPAN_B:
                pull_open.append(t)
            elif k == SPAN_E and pull_open:
                pulls.append((pull_open.pop(), t))
        elif name == "serve.pull.ep":
            if k == SPAN_B:
                ep_open.setdefault(int(b), []).append(t)
            elif k == SPAN_E and ep_open.get(int(b)):
                ep_spans.setdefault(int(b), []).append(
                    (ep_open[int(b)].pop(), t, a))
        elif name == "serve.failover" and k == SPAN_E:
            failovers += 1
    traces = []
    for tid, t0 in req_b.items():
        if tid not in req_e:
            continue
        t1, ver = req_e[tid]
        if t1 <= t0:
            continue
        iv = phase_iv.get(tid, {})
        ph = {p: 0.0 for p in SERVE_PHASES}
        for p, lst in iv.items():
            ph[p] = sum(hi - lo for lo, hi in lst)
        blocked = _overlap(iv.get("queue", []), pulls)
        ph["swap_blocked"] = blocked
        ph["queue"] = max(0.0, ph["queue"] - blocked)
        dec = iv.get("decode")
        if dec:
            ph["reply"] = max(0.0, t1 - max(hi for _, hi in dec))
        dur = t1 - t0
        traces.append({"tid": int(tid), "t_us": t1, "dur_us": dur,
                       "ver": int(ver), "phases": ph,
                       "coverage": sum(ph.values()) / dur if dur else 0.0})
    if not traces:
        return None
    traces.sort(key=lambda r: r["t_us"])
    durs = sorted(r["dur_us"] for r in traces)
    phases = {}
    for p in SERVE_PHASES:
        vals = sorted(r["phases"][p] for r in traces)
        phases[p] = {"p50_us": _pctile(vals, 50), "p99_us": _pctile(vals, 99),
                     "mean_us": sum(vals) / len(vals)}
    endpoints = {}
    for ep, lst in sorted(ep_spans.items()):
        pvals = sorted(hi - lo for lo, hi, _ in lst)
        endpoints[str(ep)] = {
            "pulls": len(lst),
            "bytes": sum(x for _, _, x in lst),
            "p50_us": _pctile(pvals, 50),
            "p99_us": _pctile(pvals, 99),
        }
    return {
        "requests": len(traces),
        "p50_us": _pctile(durs, 50),
        "p99_us": _pctile(durs, 99),
        "phases": phases,
        "endpoints": endpoints,
        "pulls": len(pulls),
        "failovers": failovers,
        "traces": traces,
    }


def serve_report() -> Optional[dict]:
    """Per-request attribution of the live ring (no dump file needed).
    None until at least one traced request completed."""
    return analyze_serve({"names": list(getattr(recorder(), "_names", [])),
                          "events": recorder().snapshot()["events"]})


def format_serve_report(rep: dict) -> str:
    lines = [f"{rep['requests']} traced requests: "
             f"p50 {rep['p50_us'] / 1e3:.3f} ms, "
             f"p99 {rep['p99_us'] / 1e3:.3f} ms "
             f"({rep['pulls']} snapshot pulls, "
             f"{rep['failovers']} failovers)"]
    for p in SERVE_PHASES:
        st = rep["phases"][p]
        lines.append(f"  {p:<13} p50 {st['p50_us'] / 1e3:9.3f} ms   "
                     f"p99 {st['p99_us'] / 1e3:9.3f} ms")
    for ep, st in rep["endpoints"].items():
        lines.append(f"  endpoint {ep}: {st['pulls']} pulls, "
                     f"{st['bytes'] / 1e6:.2f} MB, "
                     f"pull p99 {st['p99_us'] / 1e3:.3f} ms")
    return "\n".join(lines)


def format_report(rep: dict) -> str:
    lines = [f"step {rep['step']}: {rep['step_sec'] * 1e3:.2f} ms "
             f"(gossip {rep['gossip_sec'] * 1e3:.2f} ms, attribution "
             f"coverage {rep['coverage'] * 100:.0f}%)"]
    for p in ("local", "pack", "wire", "drain", "fold", "unpack",
              "compiled"):
        v = rep["phases"].get(p, 0.0)
        lines.append(f"  {p:<8} {v * 1e3:9.3f} ms")
    lines.append(f"  {'other':<7} {rep['other_sec'] * 1e3:9.3f} ms")
    if rep["edges"]:
        lines.append("  edges (deposits sent):")
        for edge in sorted(rep["edges"],
                           key=lambda e: -rep["edges"][e]["bytes"]):
            e = rep["edges"][edge]
            lines.append(
                f"    {edge:<8} {e['deposits']:3d} deposits, "
                f"{e['bytes'] / 1e6:8.2f} MB, "
                f"~{e['wire_sec_est'] * 1e3:.3f} ms wire")
    if rep["drains"]:
        lines.append("  drains (deposits folded, by origin):")
        for origin in sorted(rep["drains"]):
            d = rep["drains"][origin]
            lines.append(f"    origin {origin}: {d['deposits']} deposits, "
                         f"{d['bytes'] / 1e6:.2f} MB")
    return "\n".join(lines)
