"""Checkpoint / resume for distributed training state.

The reference has NO checkpoint subsystem (SURVEY.md §5.4: "not present");
its only related utility is initial-state broadcast. A usable TPU framework
needs one, so this is net-new capability: orbax-backed save/restore of the
rank-stacked :class:`~bluefog_tpu.optimizers.TrainState` plus host-side
counters, with the sharding layout restored on load.

Decentralized caveat handled here: every rank's parameters DIFFER between
communication rounds, so unlike data-parallel frameworks the whole
rank-stacked state must be saved, not one replica. ``save`` runs from the
controller (single-controller deployments) or from process 0 with globally
addressable arrays.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover - orbax is in the image
    _HAVE_ORBAX = False

from .optimizers import TrainState
from .runtime.logging import logger
from .runtime.state import _global_state


def _check_multicontroller_backend() -> None:
    """Fail fast when orbax's process identity would be wrong.

    Orbax coordinates multiprocess saves (primary-host finalize, commit
    barrier) through the DEFAULT backend's process identity. If the job's
    mesh lives on a different backend than the default (e.g. a multi-process
    CPU mesh while a single-process accelerator plugin is the default),
    every controller believes it is the single primary and they race on the
    rename — observed as a hang/FileExistsError. On real pods the mesh
    backend IS the default backend and orbax's standard path just works.
    """
    st = _global_state()
    if st.initialized and st.process_count > 1 \
            and jax.process_count() != st.process_count:
        raise RuntimeError(
            "multi-controller checkpointing needs the mesh backend to be "
            f"jax's default backend (mesh: {st.process_count} processes, "
            f"default backend: {jax.process_count()} process(es)); orbax "
            "coordinates its commit barrier via the default backend's "
            "process identity"
        )


def _as_tree(state: TrainState, step: int):
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "model_state": state.model_state,
        "meta": {"step": np.int64(step)},
    }


def save(path: str, state: TrainState, step: int = 0, *, force: bool = True) -> str:
    """Write a checkpoint directory at ``path`` (overwrites when ``force``)."""
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()  # never interleave with an in-flight async save
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _as_tree(state, step), force=force)
    logger.info("checkpoint saved to %s (step %d)", path, step)
    return path


_async_ckptr = None  # lazy, reused across saves (orbax guidance)
# a script whose LAST action is save_async must still commit before exit
atexit.register(lambda: wait_pending())


def save_async(path: str, state: TrainState, step: int = 0, *,
               force: bool = True) -> str:
    """Start writing a checkpoint WITHOUT blocking the training loop.

    Orbax's async path snapshots device arrays, then serializes them on a
    background thread while the next training steps run — the standard way
    to keep checkpoint cadence off the step time. A second ``save_async``
    (or a sync :func:`save`) first waits for the in-flight one;
    :func:`wait_pending` forces completion (call it before reading the
    directory or exiting). Net-new vs the reference, like the rest of this
    module (SURVEY §5.4).
    """
    global _async_ckptr
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()
    if _async_ckptr is None:
        _async_ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    path = os.path.abspath(path)
    _async_ckptr.save(path, _as_tree(state, step), force=force)
    logger.info("async checkpoint started to %s (step %d)", path, step)
    return path


def wait_pending() -> None:
    """Block until any in-flight :func:`save_async` has committed."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def restore(path: str, template: Optional[TrainState] = None):
    """Load ``(TrainState, step)`` from ``path``.

    With ``template`` (a TrainState of the right structure, e.g. from
    ``opt.init``) arrays are restored with the template's shardings —
    resuming directly onto the mesh. Without it, arrays come back as
    host-replicated values and should be re-placed via
    :func:`bluefog_tpu.shard_rank_stacked`.
    """
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()  # an in-flight async save may target this very path
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        if template is not None:
            item = _as_tree(template, 0)
            restore_args = jax.tree_util.tree_map(
                lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
                if isinstance(x, jax.Array) else ocp.RestoreArgs(),
                item,
            )
            ckpt = ckptr.restore(path, item=item, restore_args=restore_args)
        else:
            ckpt = ckptr.restore(path)
    state = TrainState(
        params=ckpt["params"],
        opt_state=ckpt["opt_state"],
        model_state=ckpt.get("model_state"),
    )
    return state, int(np.asarray(ckpt["meta"]["step"]))
