"""Checkpoint / resume for distributed training state.

The reference has NO checkpoint subsystem (SURVEY.md §5.4: "not present");
its only related utility is initial-state broadcast. A usable TPU framework
needs one, so this is net-new capability: orbax-backed save/restore of the
rank-stacked :class:`~bluefog_tpu.optimizers.TrainState` plus host-side
counters, with the sharding layout restored on load.

Decentralized caveat handled here: every rank's parameters DIFFER between
communication rounds, so unlike data-parallel frameworks the whole
rank-stacked state must be saved, not one replica. ``save`` runs from the
controller (single-controller deployments) or from process 0 with globally
addressable arrays.
"""

from __future__ import annotations

import atexit
import json
import os
import zlib
from typing import Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover - orbax is in the image
    _HAVE_ORBAX = False

from .optimizers import TrainState
from .runtime.logging import logger
from .runtime.state import _global_state


def _check_multicontroller_backend() -> None:
    """Fail fast when orbax's process identity would be wrong.

    Orbax coordinates multiprocess saves (primary-host finalize, commit
    barrier) through the DEFAULT backend's process identity. If the job's
    mesh lives on a different backend than the default (e.g. a multi-process
    CPU mesh while a single-process accelerator plugin is the default),
    every controller believes it is the single primary and they race on the
    rename — observed as a hang/FileExistsError. On real pods the mesh
    backend IS the default backend and orbax's standard path just works.
    """
    st = _global_state()
    if st.initialized and st.process_count > 1 \
            and jax.process_count() != st.process_count:
        raise RuntimeError(
            "multi-controller checkpointing needs the mesh backend to be "
            f"jax's default backend (mesh: {st.process_count} processes, "
            f"default backend: {jax.process_count()} process(es)); orbax "
            "coordinates its commit barrier via the default backend's "
            "process identity"
        )


def _as_tree(state: TrainState, step: int):
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "model_state": state.model_state,
        "meta": {"step": np.int64(step)},
    }


# World-identity sidecar written NEXT TO the orbax directory (not inside
# it: the async save path renames a temp dir onto `path` at commit time, so
# a file planted inside the final path would break the rename). JSON so
# orbax's pytree handler never has to round-trip strings.
_META_SUFFIX = ".bf_meta.json"


def _meta_path(path: str) -> str:
    return os.path.abspath(path) + _META_SUFFIX


def _topology_crc(st) -> Optional[int]:
    try:
        from . import topology as topology_util

        W = topology_util.weight_matrix(st.topology)
        return int(zlib.crc32(np.ascontiguousarray(W).tobytes()))
    except Exception:  # noqa: BLE001 — meta is best-effort
        return None


def _runtime_meta(step: int) -> dict:
    """World identity at save time: world size, topology fingerprint, and
    membership epoch — what `restore` checks so a checkpoint cannot be
    silently resumed onto a DIFFERENT world (ISSUE r9 satellite)."""
    meta = {"step": int(step)}
    st = _global_state()
    if st.initialized:
        meta["world"] = int(st.size)
        meta["process_count"] = int(st.process_count)
        crc = _topology_crc(st)
        if crc is not None:
            meta["topology_crc"] = crc
        try:
            from .runtime.heartbeat import membership_epoch

            meta["membership_epoch"] = int(membership_epoch())
        except Exception:  # noqa: BLE001
            pass
    return meta


def _write_meta(path: str, step: int) -> None:
    try:
        with open(_meta_path(path), "w") as f:
            json.dump(_runtime_meta(step), f)
    except OSError as exc:
        logger.warning("checkpoint meta sidecar write failed (%s)", exc)


def read_meta(path: str) -> Optional[dict]:
    """The checkpoint's world-identity sidecar, or None (pre-r9 or lost)."""
    try:
        with open(_meta_path(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _check_meta(path: str, strict: bool) -> None:
    meta = read_meta(path)
    st = _global_state()
    if meta is None or not st.initialized:
        return
    mismatches = []
    if "world" in meta and int(meta["world"]) != st.size:
        mismatches.append(
            f"world size {meta['world']} (saved) vs {st.size} (current)")
    crc = _topology_crc(st)
    if "topology_crc" in meta and crc is not None and \
            int(meta["topology_crc"]) != crc:
        mismatches.append(
            "topology fingerprint differs (the combine matrix changed "
            "since the save)")
    if not mismatches:
        return
    msg = (f"checkpoint {path} was saved on a different world: "
           + "; ".join(mismatches)
           + ". Decentralized state is rank-stacked — resuming it onto a "
           "mismatched world silently mis-assigns per-rank parameters.")
    if strict:
        raise RuntimeError(msg)
    logger.warning("%s Resuming anyway (pass strict=True to refuse).", msg)


def latest_path(directory: str) -> Optional[str]:
    """Newest checkpoint directory under ``directory`` (by mtime), or None.

    The elastic-rejoin fallback uses this to find the freshest local state
    when no live in-neighbor can serve a transfer."""
    try:
        entries = [os.path.join(directory, e) for e in os.listdir(directory)]
    except OSError:
        return None
    dirs = [e for e in entries if os.path.isdir(e)]
    return max(dirs, key=os.path.getmtime) if dirs else None


def save(path: str, state: TrainState, step: int = 0, *, force: bool = True) -> str:
    """Write a checkpoint directory at ``path`` (overwrites when ``force``)."""
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()  # never interleave with an in-flight async save
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _as_tree(state, step), force=force)
    _write_meta(path, step)
    logger.info("checkpoint saved to %s (step %d)", path, step)
    return path


_async_ckptr = None  # lazy, reused across saves (orbax guidance)
# a script whose LAST action is save_async must still commit before exit
atexit.register(lambda: wait_pending())


def save_async(path: str, state: TrainState, step: int = 0, *,
               force: bool = True) -> str:
    """Start writing a checkpoint WITHOUT blocking the training loop.

    Orbax's async path snapshots device arrays, then serializes them on a
    background thread while the next training steps run — the standard way
    to keep checkpoint cadence off the step time. A second ``save_async``
    (or a sync :func:`save`) first waits for the in-flight one;
    :func:`wait_pending` forces completion (call it before reading the
    directory or exiting). Net-new vs the reference, like the rest of this
    module (SURVEY §5.4).
    """
    global _async_ckptr
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()
    if _async_ckptr is None:
        _async_ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    path = os.path.abspath(path)
    _async_ckptr.save(path, _as_tree(state, step), force=force)
    # the sidecar holds host-side values known NOW; writing it immediately
    # is safe because it lives next to the orbax dir, not inside it
    _write_meta(path, step)
    logger.info("async checkpoint started to %s (step %d)", path, step)
    return path


def wait_pending() -> None:
    """Block until any in-flight :func:`save_async` has committed."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def restore(path: str, template: Optional[TrainState] = None,
            strict: bool = False):
    """Load ``(TrainState, step)`` from ``path``.

    With ``template`` (a TrainState of the right structure, e.g. from
    ``opt.init``) arrays are restored with the template's shardings —
    resuming directly onto the mesh. Without it, arrays come back as
    host-replicated values and should be re-placed via
    :func:`bluefog_tpu.shard_rank_stacked`.

    The world-identity sidecar (world size + topology fingerprint,
    recorded by ``save``/``save_async``) is checked against the current
    runtime: a mismatch WARNS by default and raises with ``strict=True`` —
    a rank-stacked checkpoint resumed onto a different world silently
    mis-assigns per-rank state.
    """
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    _check_multicontroller_backend()
    wait_pending()  # an in-flight async save may target this very path
    path = os.path.abspath(path)
    _check_meta(path, strict)
    with ocp.PyTreeCheckpointer() as ckptr:
        if template is not None:
            item = _as_tree(template, 0)
            restore_args = jax.tree_util.tree_map(
                lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
                if isinstance(x, jax.Array) else ocp.RestoreArgs(),
                item,
            )
            ckpt = ckptr.restore(path, item=item, restore_args=restore_args)
        else:
            ckpt = ckptr.restore(path)
    state = TrainState(
        params=ckpt["params"],
        opt_state=ckpt["opt_state"],
        model_state=ckpt.get("model_state"),
    )
    return state, int(np.asarray(ckpt["meta"]["step"]))
