"""bluefog_tpu: decentralized distributed training, TPU-native.

A from-scratch JAX/XLA implementation of BlueFog's capability surface
(reference: github Bluefog-Lib/bluefog, mounted at /root/reference):
decentralized data-parallel optimization over static and dynamic virtual
graph topologies, one-sided gossip windows, hierarchical averaging, classic
collectives, optimizer wrappers, a launcher, a timeline profiler.

Usage mirrors ``import bluefog.torch as bf`` (reference: torch/__init__.py:35-62):

    import bluefog_tpu as bf
    bf.init(bf.topology_util.ExponentialTwoGraph)
    x = ...  # rank-stacked array [bf.size(), ...], slice r on device r
    y = bf.neighbor_allreduce(x)

Ranks are devices of a ``jax.sharding.Mesh``; every op runs as one SPMD
program with ``ppermute``/``psum`` collectives over ICI.
"""

from . import topology as topology_util
from .version import __version__

# lifecycle + introspection
from .runtime.state import (
    init,
    shutdown,
    size,
    local_size,
    local_rank,
    rank,
    num_machines,
    machine_size,
    is_homogeneous,
    mesh,
    machine_mesh,
    set_topology,
    load_topology,
    is_topo_weighted,
    in_neighbor_ranks,
    out_neighbor_ranks,
    set_skip_negotiate_stage,
    get_skip_negotiate_stage,
    unified_mpi_window_model_supported,
    mpi_threads_supported,
    nccl_built,
)

# handles
from .runtime.handles import poll, synchronize, wait

# failure detection / coordinated shutdown / fault tolerance / elastic
# membership (multi-controller; see docs/fault_tolerance.md)
from .runtime.heartbeat import (
    dead_controllers,
    dead_ranks,
    membership_epoch,
    shutdown_requested,
    suspect_controllers,
)
from .runtime.native import (PeerLostError, QuorumLostError,
                             StaleIncarnationError)

# timeline
from .runtime.timeline import (
    start_timeline,
    stop_timeline,
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
)

# telemetry plane: metrics registry + cluster health (docs/metrics.md)
from .runtime import metrics
from .runtime.metrics import cluster_health

# flight recorder: always-on black box + postmortem dumps + step-time
# attribution (docs/flight_recorder.md)
from .runtime import flight
from .runtime.flight import step_report


def flight_dump(reason: str = "explicit", path=None):
    """Dump the flight recorder NOW (ring tail + native transport events +
    metrics snapshot) to ``bf_flight_<rank>.json`` under
    ``BLUEFOG_FLIGHT_DIR`` and, when a control plane is attached, publish
    the packed tail under ``bf.flight.<rank>`` for ``bfrun --dump``.
    Returns the local dump path (docs/flight_recorder.md)."""
    return flight.dump(reason=reason, path=path, force=True)

# ops
from .ops import (
    allgather,
    allgather_nonblocking,
    allgather_v,
    allgather_v_nonblocking,
    allreduce,
    allreduce_nonblocking,
    allreduce_,
    allreduce_nonblocking_,
    barrier,
    broadcast,
    broadcast_nonblocking,
    broadcast_,
    broadcast_nonblocking_,
    pair_gossip,
    pair_gossip_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
    CombinePlan,
    apply_plan,
    rank_sharding,
    shard_rank_stacked,
    get_win_version,
    turn_off_win_ops_with_associated_p,
    turn_on_win_ops_with_associated_p,
    win_accumulate,
    win_accumulate_nonblocking,
    win_associated_p,
    win_associated_p_all,
    win_create,
    win_fence,
    win_free,
    win_get,
    win_get_nonblocking,
    win_lock,
    win_mutex,
    win_poll,
    win_put,
    win_put_nonblocking,
    win_update,
    win_update_then_collect,
    win_wait,
)

# optimizer wrappers (reference: torch/optimizers.py)
from .optimizers import (
    TrainState,
    replicate,
    unreplicate,
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedShardedAllreduceOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)

# parameter/optimizer-state sync utilities (reference: torch/utility.py)
from .utils import (
    broadcast_parameters,
    allreduce_parameters,
    broadcast_optimizer_state,
    resnet_from_torch,
    vgg_from_torch,
)

from . import checkpoint
from . import models
from . import parallel

# serving plane: versioned snapshot distribution + batched read-only
# inference over the control-plane wire (docs/serving.md). bf.serve_client()
# attaches from inside a job; standalone serving processes import
# ``bluefog_tpu.serving`` through the lean bootstrap instead (no jax).
from .serving import RequestShed, ServeClient, serve_client
