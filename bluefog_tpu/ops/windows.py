"""One-sided "window" ops: the asynchronous gossip subsystem.

TPU-native redesign of BlueFog's MPI-RMA windows (reference API:
torch/mpi_ops.py:890-1363; CPU transport mpi_controller.cc:796-1393; GPU
emulation nccl_controller.cc:1113-1238). True one-sided RMA does not exist on
TPU, and the reference itself proves emulation is acceptable — its NCCL path
is a two-sided protocol with a passive-recv thread. Here the emulation is a
**mailbox model**: every window keeps, per rank, one receive slot per
in-neighbor — exactly the clone-per-in-neighbor layout of
WinTorchStorageManager (mpi_win_ops.cc:83-105) — plus the rank's own window
tensor.

Execution model: one window op = ONE compiled SPMD program over the rank
mesh. The mailbox is a rank-sharded array ``mail[n, d_max, ...]`` (slot k of
rank r belongs to its k-th sorted in-neighbor, the MPI_Dist_graph ordering
contract); put/get/accumulate decompose the active edge set into circulant
shifts, move data with one ``ppermute`` per shift, and blend it into the
destination slot. Per-call weights and active-edge masks are *traced*
operands, so dynamic partial-destination puts reuse the same compiled
program. ``win_update`` is a second one-program combine:
``out[r] = sw[r]*self[r] + sum_k nw[r,k]*mail[r,k]``
(DoWinSync's Sum/AvgWithNeighbor, mpi_win_ops.cc:185-238).

Semantics preserved from the reference:
  * ``self_weight`` on put/accumulate rescales the locally stored window
    tensor after the send (the push-sum "self down-weighting").
  * per-edge version counters: bumped on put/get/accumulate, cleared when
    win_update reads the buffer (mpi_controller.cc:1281-1393). Advisory, as
    in the reference. On the hosted plane origins bump BEFORE depositing
    (one batched round-trip), so a mutex-protected drain never consumes a
    deposit at version 0; the residual non-mutex race is an origin's bump
    landing before an owner's reset while its deposit lands after — the
    deposit then sits pending with version 0 until the next update folds
    it (a version poller misses that one write). Use ``require_mutex`` on
    every participant (optionally with ``BLUEFOG_WIN_STRICT=1`` to turn
    violations into errors) or ``win_fence`` where exact write/read
    ordering matters, exactly as the reference prescribes.
  * per-rank mutexes with host-side lock tables (the MPI_Fetch_and_op
    spin-lock, mpi_controller.cc:1532-1602, owned by the controller).
  * associated-p scalars: optional parallel channel carrying the push-sum
    weight, toggled globally (mpi_ops.py:1339-1363); tiny host-side numpy
    mirror of the same edge algebra.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import topology as topology_util
from . import codec as _wire_codec
from ..runtime import control_plane as _cp
from ..runtime import flight as _flight
from ..runtime import handles as _handles
from ..runtime import metrics as _metrics
from ..runtime import native as _native
from ..runtime.config import knob_env
from ..runtime.logging import logger
from ..runtime.state import _global_state
from ..runtime.timeline import (timeline_context, timeline_counter,
                                timeline_flow_finish, timeline_flow_start)
from .neighbors import _check_rank_stacked, _per_rank
from ..utils.compat import shard_map

Weights = Union[float, Dict[int, float], Dict[int, Dict[int, float]]]


def _op_timer(activity: str):
    """Step-phase latency histogram for one window op ('WIN_PUT' ->
    ``win.put_sec``): the quantitative complement of the timeline span
    emitted next to it (docs/metrics.md)."""
    ms = knob_env("BLUEFOG_PERF_GATE_DELAY_MS")
    if ms:
        # testing-only seeded slowdown: scripts/perf_gate.py's red path
        time.sleep(float(ms) / 1e3)
    return _metrics.timed(f"win.{activity[4:].lower()}_sec")


# Flow-event name binding a deposit on the origin to its drain at the owner
# (chrome flow id = the deposit tag's 39-bit (origin << 32 | counter)
# sequence, identical on both sides of the wire).
_FLOW_DEPOSIT = "WIN_DEPOSIT"


class _LocalWinHost:
    """Controller-local scalar state: versions, push-sum p, rank mutexes.

    Single-controller deployments keep the reference's cross-process
    protocols (version windows mpi_controller.cc:1281-1393, fetch-and-op
    mutexes mpi_controller.cc:1532-1602) as plain host memory — every rank
    lives in this process, so process-local IS globally consistent.
    """

    def __init__(self, name: str, n: int, d_max: int) -> None:
        self.n = n
        self.d_max = d_max
        self.version = np.zeros((n, d_max), np.int64)
        self.p = np.ones(n, np.float64)
        self.p_mail = np.zeros((n, d_max), np.float64)
        self.mutexes = [threading.RLock() for _ in range(n)]

    def bump_version(self, dst: int, k: int, force: bool = False) -> None:
        self.version[dst, k] += 1

    def bump_versions(self, pairs, force: bool = False,
                      delta: int = 1) -> None:
        for dst, k in pairs:
            self.version[dst, k] += delta

    def reset_versions(self, pairs) -> None:
        for dst, k in pairs:
            self.version[dst, k] = 0

    def get_version(self, dst: int, k: int) -> int:
        return int(self.version[dst, k])

    def get_versions(self, pairs) -> List[int]:
        return [int(self.version[dst, k]) for dst, k in pairs]

    def read_p(self) -> np.ndarray:
        return self.p.copy()

    def read_p_owned(self) -> Dict[int, float]:
        return {r: float(self.p[r]) for r in range(self.n)}

    def read_p_mail_owned(self) -> Dict[int, np.ndarray]:
        return {r: self.p_mail[r].copy() for r in range(self.n)}

    def write_p_entries(self, entries: Dict[int, float]) -> None:
        for r, v in entries.items():
            self.p[r] = v

    def write_p_mail_rows(self, rows: Dict[int, np.ndarray]) -> None:
        for r, v in rows.items():
            self.p_mail[r] = np.asarray(v, np.float64)

    def write_p(self, values: np.ndarray) -> None:
        self.p = np.asarray(values, np.float64).copy()

    def read_p_mail(self) -> np.ndarray:
        return self.p_mail.copy()

    def write_p_mail(self, values: np.ndarray) -> None:
        self.p_mail = np.asarray(values, np.float64).copy()

    def add_p_mail(self, dst: int, k: int, v: float) -> None:
        self.p_mail[dst, k] += v

    def set_p_mail(self, dst: int, k: int, v: float) -> None:
        self.p_mail[dst, k] = v

    def mutex_acquire(self, rank: int) -> None:
        self.mutexes[rank].acquire()

    def mutex_release(self, rank: int) -> None:
        self.mutexes[rank].release()

    def op_mutex_ranks(self, touched) -> List[int]:
        """Which of the touched ranks' mutexes THIS controller takes for an op."""
        return sorted(set(touched))

    def flush(self) -> None:
        pass


class _ControlPlaneWinHost:
    """Shared scalar state over the native TCP control plane.

    Multi-controller deployments (one process per host) keep window versions,
    push-sum p scalars, and rank mutexes in the job-wide control-plane server
    (csrc/bf_runtime.cc) — the analog of the reference's MPI RMA windows for
    these scalars. Writes are ownership-partitioned: only the controller
    hosting rank r's shard writes r's scalars (all controllers execute the
    same SPMD op sequence, so owner-writes gives exactly-once updates);
    ``flush`` barriers all controllers so reads after an op are consistent.
    """

    def __init__(self, name: str, n: int, d_max: int, owned: Sequence[int]) -> None:
        self.n = n
        self.d_max = d_max
        self.owned = set(owned)
        # May be a plain ControlPlaneClient or (sharded deployments) a
        # ShardRouter — the whole window plane is deliberately routing-
        # agnostic: scalars, mutexes, deposits, and drains address keys,
        # and the router owns key -> shard placement + failover
        # (docs/fault_tolerance.md, "Control-plane sharding & failover").
        self._cl = _cp.client()
        self._pre = f"w.{name}"
        # A quarantined rejoiner starts with ZERO push-sum mass: its old
        # mass died with its previous incarnation, and minting a fresh p=1
        # here would inflate the job's total — the donor mass split
        # (optimizers._PushSumRejoin) installs its share instead. It also
        # must not barrier (the aligned-creation flush below): survivors
        # are mid-loop and will never arrive.
        from ..runtime.heartbeat import quarantine_pending

        rejoining = quarantine_pending()
        p_init = 0.0 if rejoining else 1.0
        # The server lock is re-entrant per client rank but NOT
        # recursion-counted (first unlock fully releases, csrc/bf_runtime.cc
        # kUnlock). Count recursion locally so a require_mutex op nested in a
        # user win_mutex cannot release the user's lock mid-context. Each
        # rank's depth transitions AND its server lock/unlock happen under
        # one per-rank gate: a second local thread must not treat depth>0 as
        # "held" while the first is still blocked in the server lock call,
        # and must not start a fresh server acquire while a release is
        # between its depth write and its server unlock (ADVICE r3, medium).
        self._mu_depth: Dict[int, int] = {}
        self._mu_gates: Dict[int, threading.Lock] = {}
        self._mu_depth_lock = threading.Lock()
        for dst in self.owned:
            _cp.put_float(self._cl, f"{self._pre}.p.{dst}", p_init)
            for k in range(d_max):
                self._cl.put(f"{self._pre}.v.{dst}.{k}", 0)
                _cp.put_float(self._cl, f"{self._pre}.m.{dst}.{k}", 0.0)
        if not rejoining:
            self.flush()

    def bump_version(self, dst: int, k: int, force: bool = False) -> None:
        # ``force``: origin-side bump in the hosted (one-sided) plane — slot
        # (dst, k) maps 1:1 to a source rank, so the origin may bump a
        # non-owned destination's counter without write contention.
        if force or dst in self.owned:
            self._cl.fetch_add(f"{self._pre}.v.{dst}.{k}", 1)

    def bump_versions(self, pairs, force: bool = False,
                      delta: int = 1) -> None:
        """Batched bump: n touched edges, ONE pipelined round-trip (ADVICE
        r3: the per-edge fetch_add re-introduced n-scaling latency on the
        hosted hot path). ``delta=-1`` is the rollback path for deposits
        that never landed."""
        keys = [f"{self._pre}.v.{dst}.{k}" for dst, k in pairs
                if force or dst in self.owned]
        if keys:
            self._cl.fetch_add_many(keys, deltas=[delta] * len(keys))

    def reset_versions(self, pairs) -> None:
        keys = [f"{self._pre}.v.{dst}.{k}" for dst, k in pairs
                if dst in self.owned]
        if keys:
            self._cl.put_many(keys, [0] * len(keys))

    def get_version(self, dst: int, k: int) -> int:
        return int(self._cl.get(f"{self._pre}.v.{dst}.{k}"))

    def get_versions(self, pairs) -> List[int]:
        return [int(v) for v in self._cl.get_many(
            [f"{self._pre}.v.{dst}.{k}" for dst, k in pairs])]

    @staticmethod
    def _bits_to_float(v: int) -> float:
        import struct as _st
        return _st.unpack("<d", _st.pack("<q", v))[0]

    @staticmethod
    def _float_to_bits(v: float) -> int:
        import struct as _st
        return _st.unpack("<q", _st.pack("<d", float(v)))[0]

    def read_p(self) -> np.ndarray:
        vals = self._cl.get_many(
            [f"{self._pre}.p.{r}" for r in range(self.n)])
        return np.array([self._bits_to_float(v) for v in vals])

    def read_p_owned(self) -> Dict[int, float]:
        """Batched read of only this controller's ranks (the hosted hot
        path: one pipelined round-trip, no n-scaling)."""
        owned = sorted(self.owned)
        vals = self._cl.get_many([f"{self._pre}.p.{r}" for r in owned])
        return {r: self._bits_to_float(v) for r, v in zip(owned, vals)}

    def read_p_mail_owned(self) -> Dict[int, np.ndarray]:
        owned = sorted(self.owned)
        keys = [f"{self._pre}.m.{r}.{k}"
                for r in owned for k in range(self.d_max)]
        vals = self._cl.get_many(keys)
        out: Dict[int, np.ndarray] = {}
        i = 0
        for r in owned:
            out[r] = np.array([self._bits_to_float(v)
                               for v in vals[i:i + self.d_max]])
            i += self.d_max
        return out

    def write_p_entries(self, entries: Dict[int, float]) -> None:
        items = sorted(entries.items())
        self._cl.put_many([f"{self._pre}.p.{r}" for r, _ in items],
                          [self._float_to_bits(v) for _, v in items])

    def write_p_mail_rows(self, rows: Dict[int, np.ndarray]) -> None:
        keys, vals = [], []
        for r in sorted(rows):
            for k in range(self.d_max):
                keys.append(f"{self._pre}.m.{r}.{k}")
                vals.append(self._float_to_bits(float(rows[r][k])))
        self._cl.put_many(keys, vals)

    def write_p(self, values: np.ndarray) -> None:
        for r in self.owned:
            _cp.put_float(self._cl, f"{self._pre}.p.{r}", float(values[r]))

    def read_p_mail(self) -> np.ndarray:
        out = np.zeros((self.n, self.d_max), np.float64)
        for r in range(self.n):
            for k in range(self.d_max):
                out[r, k] = _cp.get_float(self._cl, f"{self._pre}.m.{r}.{k}")
        return out

    def write_p_mail(self, values: np.ndarray) -> None:
        for r in self.owned:
            for k in range(self.d_max):
                _cp.put_float(self._cl, f"{self._pre}.m.{r}.{k}",
                              float(values[r, k]))

    def add_p_mail(self, dst: int, k: int, v: float) -> None:
        if dst in self.owned:
            key = f"{self._pre}.m.{dst}.{k}"
            _cp.put_float(self._cl, key, _cp.get_float(self._cl, key) + v)

    def set_p_mail(self, dst: int, k: int, v: float) -> None:
        if dst in self.owned:
            _cp.put_float(self._cl, f"{self._pre}.m.{dst}.{k}", v)

    def _mu_gate(self, rank: int) -> threading.Lock:
        with self._mu_depth_lock:
            gate = self._mu_gates.get(rank)
            if gate is None:
                gate = self._mu_gates[rank] = threading.Lock()
            return gate

    def mutex_acquire(self, rank: int) -> None:
        # The gate is held ACROSS the blocking server call: a second local
        # thread arriving mid-acquire waits here (equivalent to waiting on
        # the server) instead of seeing depth>0 and entering the
        # "mutex-protected" region before the lock is actually granted.
        from ..runtime.native import PeerLostError

        with self._mu_gate(rank):
            depth = self._mu_depth.get(rank, 0)
            if depth == 0:
                try:
                    # bfcheck: ok-blocking-under-lock (the gate exists to
                    # serialize local threads THROUGH this server acquire;
                    # waiting on the gate is equivalent to waiting on the
                    # server, and the gate is per-rank so nothing else
                    # stalls)
                    self._cl.lock(f"{self._pre}.mu.{rank}")
                except PeerLostError as exc:
                    # typed + attributed: the caller (window optimizers'
                    # self-healing retry, or user code) learns WHICH rank's
                    # mutex had a dead holder; the lock itself was left
                    # free, so a retried acquire succeeds.
                    raise PeerLostError(
                        f"window mutex for rank {rank}: holder died "
                        f"mid-hold ({exc.args[0] if exc.args else exc}); "
                        "re-acquire to continue on the shrunken topology",
                        dead=exc.dead) from exc
            self._mu_depth[rank] = depth + 1

    def mutex_release(self, rank: int) -> None:
        # Same gate across the unlock: a fresh acquirer cannot slip in
        # between the depth write and the server unlock (the server lock is
        # re-entrant per controller, so it would be granted instantly and
        # then released out from under the new holder).
        from ..runtime.native import PeerLostError

        with self._mu_gate(rank):
            depth = self._mu_depth.get(rank, 0) - 1
            if depth < 0:
                raise RuntimeError(f"mutex for rank {rank} released more "
                                   "times than acquired")
            self._mu_depth[rank] = depth
            if depth == 0:
                try:
                    self._cl.unlock(f"{self._pre}.mu.{rank}")
                except PeerLostError as exc:
                    # The lock was force-released OUT FROM UNDER this
                    # holder (lease expiry, or our connection dropped and
                    # transparently reconnected mid-hold): the exclusion
                    # this critical section assumed may have been broken.
                    # Release paths run in finally blocks — raising here
                    # would mask the section's actual result — so warn
                    # loudly instead; the data-plane protocols tolerate
                    # the advisory race (module header) and the next
                    # acquire starts a clean epoch.
                    logger.warning(
                        "window mutex for rank %d was force-released while "
                        "held (%s): exclusion may have been broken for the "
                        "section just completed", rank, exc)

    def op_mutex_ranks(self, touched) -> List[int]:
        # Owner-partitioned: each controller locks only the touched ranks it
        # owns. Owned sets are disjoint, so the collective op cannot deadlock
        # between controllers, yet an external mutex holder still excludes it.
        return sorted(set(touched) & self.owned)

    def flush(self) -> None:
        _cp.barrier(self._pre)


def _win_acc_dtype(dtype):
    """Accumulation dtype for weighted mailbox math.

    Fractional edge weights demand float arithmetic even for integer
    windows (the replaced eager implementation got this from JAX's weak
    python-float promotion); low-precision floats accumulate in f32.
    """
    dtype = jnp.dtype(dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        return jnp.float32
    return jnp.float32 if dtype.itemsize < 4 else dtype


class _GraphLayout:
    """Static decomposition of the window's edge set into circulant shifts."""

    def __init__(self, topology, n: int) -> None:
        self.n = n
        self.in_nbrs = {
            r: topology_util.in_neighbor_ranks(topology, r) for r in range(n)
        }
        self.out_nbrs = {
            r: topology_util.out_neighbor_ranks(topology, r) for r in range(n)
        }
        self.d_max = max((len(v) for v in self.in_nbrs.values()), default=0) or 1
        shifts = sorted({
            (dst - src) % n
            for dst, srcs in self.in_nbrs.items() for src in srcs
        })
        self.shifts: Tuple[int, ...] = tuple(shifts)
        self.shift_index = {s: i for i, s in enumerate(shifts)}
        S = max(len(shifts), 1)
        # slot[si, dst] = mailbox slot of src=(dst-si_shift)%n at dst; 0 when
        # the edge doesn't exist (guarded by a zero active mask at runtime).
        self.slot = np.zeros((S, n), np.int32)
        self.has_edge = np.zeros((S, n), bool)
        self.slot_of = {
            r: {src: k for k, src in enumerate(self.in_nbrs[r])}
            for r in range(n)
        }
        for si, s in enumerate(shifts):
            for dst in range(n):
                src = (dst - s) % n
                k = self.slot_of[dst].get(src)
                if k is not None:
                    self.slot[si, dst] = k
                    self.has_edge[si, dst] = True


_legacy_plane_warned = False


def _plane_policy() -> Tuple[str, Optional[bool]]:
    """Resolve the window-plane policy: ``(policy, hosted_forced)``.

    ``policy`` is ``BLUEFOG_WIN_PLANE`` — ``auto`` (per-edge planner over a
    hosted window), ``compiled`` (collective plane forced), or ``hosted``
    (mailbox plane forced, planner off: the r6/r7 wire bit for bit).
    ``hosted_forced`` overrides the window-plane default (hosted iff
    multi-controller): True/False force it, None keeps the default.

    The legacy ``BLUEFOG_WIN_HOST_PLANE`` knob is an alias: ``1`` maps to
    ``hosted`` and ``0`` to ``compiled`` (with a one-time deprecation
    warning), so every existing config keeps its exact pre-planner
    behavior. When BOTH knobs are set, the new knob's policy wins while
    the legacy knob still forces window hostedness — that combination
    (``BLUEFOG_WIN_PLANE=auto BLUEFOG_WIN_HOST_PLANE=1``) is how a
    single-controller harness gets a hosted window WITH the planner, the
    shape the hybrid bench and equivalence tests run (docs/window_planes.md).
    """
    global _legacy_plane_warned
    raw = knob_env("BLUEFOG_WIN_PLANE")
    legacy = knob_env("BLUEFOG_WIN_HOST_PLANE")  # True / False / None
    if raw:
        raw = str(raw).lower()
        if raw not in ("auto", "compiled", "hosted"):
            logger.warning(
                "BLUEFOG_WIN_PLANE=%r is not auto|compiled|hosted; "
                "treating it as auto", raw)
            raw = "auto"
        if raw == "hosted":
            return "hosted", True
        if raw == "compiled":
            return "compiled", False
        return "auto", legacy
    if legacy is not None:
        if not _legacy_plane_warned:
            _legacy_plane_warned = True
            logger.warning(
                "BLUEFOG_WIN_HOST_PLANE is deprecated: use "
                "BLUEFOG_WIN_PLANE=%s instead (see MIGRATION.md; the "
                "legacy knob keeps its exact pre-r13 behavior — it also "
                "pins the per-edge plane planner OFF)",
                "hosted" if legacy else "compiled")
        return ("hosted" if legacy else "compiled"), legacy
    return "auto", None


def _hosted_mode_enabled(policy: Optional[Tuple[str, Optional[bool]]] = None
                         ) -> bool:
    """Whether new windows use the hosted (host-tensor-transport) data plane.

    Default policy: ON for multi-controller jobs with a control plane (the
    deployments where the collective plane's all-controllers-must-dispatch
    contract breaks asynchrony), OFF for single-controller (the compiled
    ppermute plane is strictly faster on-device and the controller owns all
    ranks anyway). ``BLUEFOG_WIN_PLANE`` / the legacy
    ``BLUEFOG_WIN_HOST_PLANE`` force either way (:func:`_plane_policy`).
    """
    if not _cp.active():
        return False
    _, forced = policy if policy is not None else _plane_policy()
    if forced is not None:
        return forced
    return _cp.world() > 1


def _owned_rows(tensor, owned) -> Dict[int, np.ndarray]:
    """Extract this controller's rank rows of a rank-stacked tensor as numpy.

    Works for host arrays, fully-addressable device arrays, and
    multi-controller global arrays (via addressable_shards)."""
    if isinstance(tensor, jax.Array) and not tensor.is_fully_addressable:
        rows: Dict[int, np.ndarray] = {}
        for shard in tensor.addressable_shards:
            idx = shard.index[0]
            r0 = idx.start or 0
            data = np.asarray(shard.data)
            for i in range(data.shape[0]):
                rows[r0 + i] = data[i]
        missing = set(owned) - set(rows)
        if missing:
            raise ValueError(
                f"input tensor is missing addressable rows for owned ranks "
                f"{sorted(missing)}")
        return {r: rows[r] for r in owned}
    host = np.asarray(tensor)
    return {r: np.array(host[r]) for r in owned}


class Window:
    """Mailbox state for one named window over the current topology.

    Two data planes:

    * **collective** (single-controller default): one compiled SPMD program
      per op — ppermute per circulant shift, on-device mailbox blend.
    * **hosted** (multi-controller default; the reference's one-sided
      semantics): tensors move through the control-plane server's bulk-bytes
      mailboxes (csrc/bf_runtime.cc kAppendBytes/kTakeBytes). An origin
      controller deposits into a remote rank's server mailbox and returns —
      the target drains deposits at ITS next win_update, so a slow or
      sleeping controller never blocks a fast one (the property the
      reference gets from passive-target MPI_Win_lock RMA,
      mpi_controller.cc:953-1034, and its NCCL passive-recv thread,
      nccl_controller.cc:1113-1238). Each rank's current window tensor is
      also published to the server (the "exposed window" copy) so win_get
      stays one-sided.
    """

    def __init__(self, name: str, tensor, zero_init: bool) -> None:
        st = _global_state()
        self.name = name
        self.size = st.size
        # Edges are frozen at creation time, like MPI_Win_create against the
        # GRAPH communicator; topology changes are rejected while windows
        # exist (state.set_topology).
        self.layout = _GraphLayout(st.topology, st.size)
        self.in_neighbors = self.layout.in_nbrs
        self.out_neighbors = self.layout.out_nbrs
        d = self.layout.d_max
        # Mailboxes for integer windows store floats: weighted contributions
        # stay exact until win_update casts the combined result back.
        self.dtype = jnp.dtype(tensor.dtype)
        mail_dtype = self.dtype if jnp.issubdtype(self.dtype, jnp.floating) \
            else jnp.dtype(jnp.float32)
        self.mail_dtype = mail_dtype
        self.row_shape = tuple(tensor.shape[1:])
        # Collective-plane mailboxes carry one extra SCRATCH slot (index
        # d_max): the compiled exchange redirects inactive-edge writes there
        # so the put path stays write-only (see _exchange_fn). The hosted
        # plane's host-side rows don't need it.
        mail_shape = (st.size, d + 1) + self.row_shape
        policy = getattr(st, "win_plane", None) or _plane_policy()
        self.plane = policy[0]
        self.hosted = _hosted_mode_enabled(policy)
        # Wire codec (ISSUE r15, docs/compression.md): resolved once per
        # window from the registry knob. On the hosted plane it transforms
        # every deposit payload (and the matching local folds, so a
        # single-controller hosted harness sees the same numerics as a
        # cross-controller wire); on the compiled plane the quantization
        # codecs apply through the mail-dtype blend (codec.quantize_blend)
        # while top-k — index records over a dense exchange — does not.
        # None keeps the legacy wire byte-identical (test-pinned).
        # Per-edge overrides (ISSUE r16, docs/self_tuning.md): the grammar
        # extends to ``<spec>(;<src>><dst>=<spec>)*`` and the tuner mutates
        # the override map at runtime via set_edge_codec; an empty map
        # keeps every path byte-identical to the window-level codec.
        self.codec, _edge_over = _wire_codec.resolve_edge_spec(
            knob_env("BLUEFOG_WIN_CODEC"))
        self._edge_codec: Dict[Tuple[int, int],
                               Optional[_wire_codec.WireCodec]] = \
            dict(_edge_over)
        # Sharded window plane (ISSUE r17, docs/sharded_windows.md): when
        # a window carries rotating shard rows, the optimizer binds the
        # shard factor and advances the active shard index every gossip
        # step. Deposits then carry the shard index on the wire so an
        # owner whose rotation drifted from an origin's NEVER folds a
        # different shard's coordinates into its slots (the value is
        # dropped with a counter; the exact-mass p contribution still
        # folds). factor 1 / shard -1 keeps the legacy wire byte-identical.
        self.shard_factor = 1
        self.active_shard = -1
        # Error-feedback state (top-k): one acc-dtype row per owned source
        # rank, held next to the fused flat window the optimizers pack
        # (optimizers._WindowOptimizer). `_ef_rows` is the residual/unsent
        # gap; `_ef_ref` is the put-mode CHOCO estimate x̂ — seeded below
        # from the creation-time rows so it starts aligned with the
        # mailbox slots' initial copies (zero_init windows start at 0).
        self._ef_rows: Dict[int, np.ndarray] = {}
        self._ef_ref: Dict[int, np.ndarray] = {}
        # Per-edge estimator state (ISSUE r16): edges carrying a codec
        # override keep their OWN residual/reference rows keyed (src, dst)
        # — the shared per-src state above stays byte-identical for every
        # edge still on the window codec. A missing ref for an EF-put edge
        # means "needs rebase": the next send ships the full row through
        # the codec's state fallback as a PUT (see _encode_edge).
        self._ef_edge_rows: Dict[Tuple[int, int], np.ndarray] = {}
        self._ef_edge_ref: Dict[Tuple[int, int], np.ndarray] = {}
        # Scalar protocols (versions / push-sum p / mutexes): controller-local
        # host memory, or the job-wide control plane when one is attached
        # (multi-controller; reference mpi_controller.cc:1281-1393, 1532-1602).
        if _cp.active():
            # st.process_index, not argless jax.process_index(): the mesh's
            # backend may not be the default backend (state.py init).
            owned = _cp.owned_ranks(st.devices, st.process_index)
            self.host = _ControlPlaneWinHost(name, st.size, self.layout.d_max,
                                             owned)
        else:
            owned = list(range(st.size))
            self.host = _LocalWinHost(name, st.size, self.layout.d_max)
        self.owned = sorted(owned)
        # Per-edge plane planner (hosted windows under the auto policy
        # only): decides which frozen edges ride the compiled fast path
        # and which stay on the mailbox residual (ops/plan.py).
        self._planner = None
        self._local_mesh = None
        self._hybrid_cache: Dict[Tuple, object] = {}
        if self.hosted and self.plane == "auto":
            from .plan import PlanePlanner

            min_mb = knob_env("BLUEFOG_WIN_PLAN_MIN_MB") or 0.0
            self._planner = PlanePlanner(
                st.size,
                [(src, dst) for dst, srcs in self.in_neighbors.items()
                 for src in srcs],
                {r: getattr(st.devices[r], "process_index", 0)
                 for r in range(st.size)},
                row_bytes=int(np.prod(self.row_shape, dtype=np.int64))
                * self.dtype.itemsize,
                min_bytes=int(float(min_mb) * (1 << 20)),
                # the codec shrinks every hosted deposit, so the planner's
                # static size floor must judge POST-codec bytes — measured
                # attribution (already on-wire) overrides this estimate
                wire_scale=(self.codec.nominal_ratio
                            if self.codec is not None else 1.0))
            # per-edge overrides shrink (or restore) individual edges: the
            # planner's floor must judge each edge's own on-wire bytes
            for _e, _c in self._edge_codec.items():
                self._planner.set_edge_scale(
                    _e, _c.nominal_ratio if _c is not None else 1.0)

        if self.hosted:
            # defensive: discard any deposit records a crashed predecessor
            # window of the same name left on the server
            cl = _cp.client()
            for r in self.owned:
                for k in range(self.layout.d_max):
                    while cl.take_bytes(self._dep_key(r, k)):
                        pass
            rows = _owned_rows(tensor, self.owned)
            self._rows = {r: v.astype(self.dtype) for r, v in rows.items()}
            if self.codec is not None and self.codec.error_feedback:
                acc_t = np.dtype(_win_acc_dtype(mail_dtype))
                self._ef_ref = {
                    r: (np.zeros(self.row_shape, acc_t) if zero_init
                        else self._rows[r].astype(acc_t))
                    for r in self.owned}
            # grammar-configured EF edges seed their reference exactly like
            # the window-level codec (the mailbox slots start as the same
            # creation-time copies); runtime switches instead start with no
            # ref and rebase on first send
            acc_t = np.dtype(_win_acc_dtype(mail_dtype))
            for (_s, _d), _c in self._edge_codec.items():
                if _c is not None and _c.error_feedback and _s in owned:
                    self._ef_edge_ref[(_s, _d)] = (
                        np.zeros(self.row_shape, acc_t) if zero_init
                        else self._rows[_s].astype(acc_t))
            if zero_init:
                self._mail_rows = {
                    r: np.zeros((d,) + self.row_shape, mail_dtype)
                    for r in self.owned}
            else:
                self._mail_rows = {
                    r: np.broadcast_to(
                        self._rows[r][None], (d,) + self.row_shape
                    ).astype(mail_dtype).copy()
                    for r in self.owned}
            self._publish_selves(self.owned)
            # creation is aligned across controllers (like MPI_Win_create);
            # data-plane OPS afterwards never barrier — that's the point.
            # EXCEPT for a quarantined rejoiner: the survivors are mid-loop
            # and will never arrive at a creation barrier — its window
            # joins one-sidedly and state transfer replaces the rows anyway.
            from ..runtime.heartbeat import quarantine_pending

            if not quarantine_pending():
                self.host.flush()
        else:
            sh = NamedSharding(st.mesh, P("rank"))
            if isinstance(tensor, jax.Array):
                # Device input (possibly a multi-controller global array that
                # CANNOT be materialized on the host): reshard directly, and
                # build the neighbor-buffer copy with eager device ops — every
                # controller executes the same sequence, so this is SPMD-safe.
                self._self_value = jax.device_put(tensor, sh)
                if zero_init:
                    mail = jax.device_put(np.zeros(mail_shape, mail_dtype), sh)
                else:
                    # Neighbor buffers start as a copy of the local tensor
                    # (mpi_ops.py:890-915 zero_init=False default).
                    mail = jnp.broadcast_to(
                        self._self_value[:, None], mail_shape).astype(mail_dtype)
                    mail = jax.device_put(mail, sh)
            else:
                # Host input: stage via numpy so nothing hops through the
                # DEFAULT device, which may be a different backend than the
                # window's mesh (e.g. a remote TPU while the mesh is CPU).
                host = np.asarray(tensor)
                self._self_value = jax.device_put(host, sh)
                if zero_init:
                    mail = np.zeros(mail_shape, mail_dtype)
                else:
                    mail = np.broadcast_to(host[:, None], mail_shape).astype(
                        mail_dtype)
                mail = jax.device_put(mail, sh)
            self.mail = mail
        # Serializes the whole-array read-modify-write of mail/self_value:
        # ops touching disjoint edges hold disjoint rank mutexes yet still
        # reassign the same arrays, so every op takes this lock around its
        # dispatch (the rank mutexes keep their reference semantics of
        # protecting a rank's buffers across ops).
        self.state_mu = threading.RLock()
        self._exchange_cache: Dict[Tuple, object] = {}
        self._update_cache: Dict[Tuple, object] = {}
        # Monotonic deposit sequence for the tagged wire (one counter per
        # window per controller suffices: every mailbox key has exactly one
        # writing controller and state_mu serializes its deposits).
        self._dep_seq = 0

    # -- sharded rotation (ISSUE r17) --------------------------------------

    def bind_shard(self, factor: int, start: int = 0) -> None:
        """Declare this window's rows as rotating shard rows (the window
        optimizer calls this once right after win_create)."""
        self.shard_factor = max(1, int(factor))
        self.active_shard = int(start) if self.shard_factor > 1 else -1
        _metrics.gauge("win.shard_factor").set(float(self.shard_factor))

    def set_active_shard(self, shard: int) -> None:
        """Advance the rotation (called before each sharded gossip step's
        ops; serialized against the drain by state_mu)."""
        with self.state_mu:
            self.active_shard = int(shard) % self.shard_factor

    # -- self_value: a property so both planes share the publish contract ---

    @property
    def self_value(self):
        if not self.hosted:
            return self._self_value
        return _assemble_global(self, self._rows)

    @self_value.setter
    def self_value(self, value) -> None:
        if not self.hosted:
            self._self_value = value
            return
        rows = _owned_rows(value, self.owned)
        with self.state_mu:
            for r in self.owned:
                self._rows[r] = np.asarray(rows[r]).astype(self.dtype)
            self._publish_selves(self.owned)

    # -- hosted-plane internals --------------------------------------------

    def _self_key(self, rank: int) -> str:
        return f"w.{self.name}.self.{rank}"

    def _dep_key(self, dst: int, k: int) -> str:
        return f"w.{self.name}.dep.{dst}.{k}"

    def _sidx_key(self, rank: int) -> str:
        return f"w.{self.name}.sidx.{rank}"

    def read_published_shard(self, rank: int):
        """``(row, shard_index)`` of a rank's published tensor on a
        sharded window (shard_index is None when the owner never
        published or the window is unsharded). The rejoin reassembly
        polls this across a donor's gossip steps until it has collected
        every shard (docs/sharded_windows.md)."""
        sidx = None
        if self.shard_factor > 1:
            try:
                v = int(_cp.client().get(self._sidx_key(rank)))
            except (OSError, RuntimeError):
                v = 0
            sidx = (v - 1) if v > 0 else None
        return self.read_published_row(rank), sidx

    def _publish_self(self, rank: int) -> None:
        """Refresh rank's 'exposed window' copy on the server (win_get)."""
        self._publish_selves([rank])

    def _publish_selves(self, ranks) -> None:
        """Batched publish: all owned rows in one pipelined round-trip.

        Rows go out as uint8 views (always exportable, even for ml_dtypes
        extension floats) through the native scatter-gather write — a
        100 MB publish costs zero Python-side copies, where ``tobytes()``
        duplicated every published byte (this is half the win_update wire
        traffic at ResNet scale).

        Quantization codecs (``state_codec``) compress the published copy
        too — the publish is the OTHER half of win_update's wire bytes
        and the whole of win_get's pull — behind a 4-byte magic + codec
        id header; every reader goes through :meth:`_parse_published`,
        which keeps raw rows (codec ``none``, and top-k windows, whose
        sparse records cannot carry absolute state) byte-identical."""
        ranks = list(ranks)
        if not ranks:
            return
        if self.shard_factor > 1:
            # rotation index published NEXT TO the rows (one pipelined
            # put_many): a donor/rejoiner reading a published row must
            # know WHICH shard's coordinates it carries
            _cp.client().put_many(
                [self._sidx_key(r) for r in ranks],
                [self.active_shard + 1] * len(ranks))
        # Published-state codec: the configured codec itself for the
        # quantizers, the int8 absolute-state fallback for top-k (sparse
        # records cannot carry absolute state — codec.state_codec_for),
        # raw legacy rows when no codec is configured.
        pub = _wire_codec.state_codec_for(self.codec)
        if pub is not None:
            blobs = []
            raw_b = wire_b = 0
            for r in ranks:
                enc = pub.encode(self._rows[r])
                blob = np.empty(_PUB_HDR + enc.nbytes, np.uint8)
                blob[:_PUB_HDR] = np.frombuffer(
                    struct.pack("<IBBH", _PUB_MAGIC, pub.cid, 0, 0),
                    np.uint8)
                blob[_PUB_HDR:] = enc
                blobs.append(blob)
                raw_b += self._rows[r].nbytes
                wire_b += blob.nbytes
            _metrics.counter("win.codec.raw_bytes").inc(raw_b)
            _metrics.counter("win.codec.wire_bytes").inc(wire_b)
            _cp.client().put_bytes_many(
                [self._self_key(r) for r in ranks], blobs)
            return
        _cp.client().put_bytes_many(
            [self._self_key(r) for r in ranks],
            [np.ascontiguousarray(self._rows[r]).reshape(-1).view(
                np.uint8) for r in ranks])

    def _read_remote_self(self, rank: int) -> np.ndarray:
        return self._read_remote_selves([rank])[0]

    def _parse_published(self, rank: int, buf) -> np.ndarray:
        """Published payload -> row array: a raw wire-dtype row (codec
        ``none`` / top-k — byte-identical to the legacy format) or a
        magic-prefixed codec-encoded state row (``_publish_selves``).
        The codec id comes from the PAYLOAD, never this window's env —
        origin and reader may disagree safely."""
        expect = int(np.prod(self.row_shape, dtype=np.int64)) * \
            self.dtype.itemsize
        n = len(buf)
        if n == expect:
            return np.frombuffer(buf, self.dtype).reshape(self.row_shape)
        if n > _PUB_HDR:
            magic, cid = struct.unpack_from("<IB", buf, 0)
            if magic == _PUB_MAGIC:
                count = int(np.prod(self.row_shape, dtype=np.int64))
                flat = _wire_codec.by_id(cid).decode(
                    np.frombuffer(buf, np.uint8)[_PUB_HDR:],
                    self.dtype, count)
                return flat.reshape(self.row_shape)
        raise RuntimeError(
            f"window '{self.name}': published tensor for rank "
            f"{rank} has {n} bytes, expected {expect} (raw) or an "
            "encoded-state payload")

    def _read_remote_selves(self, ranks) -> List[np.ndarray]:
        """Batched read of published tensors: one pipelined round-trip."""
        ranks = list(ranks)
        if not ranks:
            return []
        raws = _cp.client().get_bytes_many(
            [self._self_key(r) for r in ranks])
        return [self._parse_published(rank, raw)
                for rank, raw in zip(ranks, raws)]

    def _read_remote_self_view(self, rank: int):
        """One published row as a zero-copy array over the native reply.

        Returns ``(row, owner)``; the caller folds the row and then
        ``owner.close()``. Large rows arrive as concurrent byte-range
        stripes over the connection pool (``get_bytes_view``); the win_get
        pipeline additionally keeps several sources in flight at once, so
        the pool stays saturated while earlier sources fold. (Encoded
        state rows decode into a fresh array; the owner close stays the
        caller's job either way.)"""
        view, owner = _cp.client().get_bytes_view(self._self_key(rank))
        row = self._parse_published(rank, view)
        return row, owner

    def _fold_record(self, dst: int, k: int, mode: int,
                     contrib: np.ndarray) -> None:
        """Fold one deposit into the local mailbox slot (owner side).

        Same cast discipline as the compiled plane: accumulate in the acc
        dtype, cast back to the mail dtype per record. Wide-enough
        mailboxes (f32/f64 — the mail dtype IS an acc dtype) fold in one
        in-place pass instead of the cast-add-cast-store four."""
        acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
        slot = self._mail_rows[dst][k]
        if mode == _DEP_ACC:
            if np.dtype(self.mail_dtype) == acc_t:
                np.add(slot, contrib.astype(acc_t, copy=False), out=slot)
            else:
                slot[...] = (slot.astype(acc_t) +
                             contrib.astype(acc_t)).astype(self.mail_dtype)
        else:
            np.copyto(slot, contrib, casting="unsafe")

    def ef_residual(self, src: int) -> np.ndarray:
        """The error-feedback residual row for owned source ``src`` (zeros
        until the first compressed send). Held in the acc dtype so
        repeated compensate/subtract cycles never lose mass to rounding
        below the wire's own precision."""
        r = self._ef_rows.get(src)
        if r is None:
            acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
            r = self._ef_rows[src] = np.zeros(self.row_shape, acc_t)
        return r

    def ef_residual_norm(self) -> float:
        """L2 norm over every owned rank's residual (0.0 when EF is off
        or nothing compressed yet) — the ``win.codec.residual_norm``
        gauge's source."""
        if not self._ef_rows and not self._ef_edge_rows:
            return 0.0
        return float(np.sqrt(
            sum(float(np.sum(np.square(r, dtype=np.float64)))
                for r in self._ef_rows.values())
            + sum(float(np.sum(np.square(r, dtype=np.float64)))
                  for r in self._ef_edge_rows.values())))

    def ef_edge_residual_norm(self, src: int, dst: int) -> float:
        """L2 norm of one overridden edge's own residual (0.0 when the
        edge rides the window codec or nothing compressed yet) — the
        tuner's per-edge de-escalation sensor."""
        r = self._ef_edge_rows.get((int(src), int(dst)))
        if r is None:
            return 0.0
        return float(np.sqrt(np.sum(np.square(r, dtype=np.float64))))

    def codec_for(self, src: int, dst: int):
        """Effective wire codec for edge ``src -> dst``: the per-edge
        override when one is set, else the window codec."""
        try:
            return self._edge_codec[(int(src), int(dst))]
        except KeyError:
            return self.codec

    def set_edge_codec(self, src: int, dst: int, spec) -> bool:
        """Switch one edge's wire codec at runtime (the tuner's codec
        lever, ISSUE r16). ``spec`` is the single-codec grammar (``none``
        / ``int8`` / ``fp8`` / ``topk:<frac>``), a WireCodec, or None.

        Switch protocol (docs/self_tuning.md):

        * TO an error-feedback codec in put mode: the per-edge CHOCO
          reference starts absent, so the first post-switch send REBASES —
          it ships the full row through the codec's state fallback (int8)
          as a plain PUT, then both ends agree on x̂ and deltas resume
          (mailbox FIFO ordering makes this race-free).
        * AWAY from error feedback: the put-mode reference is dropped (the
          next full PUT supersedes the unsent gap); any accumulate-mode
          residual is KEPT and folded into the next send's base whatever
          the new codec, so push-sum numerator mass is never lost across
          a switch — the associated-p channel ships exact in the header
          either way.

        Returns True when the effective codec actually changed."""
        edge = (int(src), int(dst))
        new = _wire_codec.resolve(spec) if isinstance(spec, str) or \
            spec is None else spec
        cur = self.codec_for(*edge)

        def _key(c):
            return None if c is None else (c.cid, getattr(c, "frac", None))

        if _key(new) == _key(cur):
            return False
        if _key(new) == _key(self.codec):
            self._edge_codec.pop(edge, None)
        else:
            self._edge_codec[edge] = new
        if new is None or not new.error_feedback:
            self._ef_edge_ref.pop(edge, None)
        if self._planner is not None:
            self._planner.set_edge_scale(
                edge, new.nominal_ratio if new is not None else 1.0)
        _metrics.counter("win.codec.edge_switches").inc()
        return True

    def _edge_residual(self, edge: Tuple[int, int]) -> np.ndarray:
        r = self._ef_edge_rows.get(edge)
        if r is None:
            acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
            r = self._ef_edge_rows[edge] = np.zeros(self.row_shape, acc_t)
        return r

    def _edge_raw_base(self, edge: Tuple[int, int], x: np.ndarray,
                       mode: int) -> np.ndarray:
        """Send base for a raw (codec None) override edge: an accumulate
        folds any residual mass a previous EF codec left behind (exact —
        the uncompressed wire ships it all), a put supersedes it."""
        e = self._ef_edge_rows.pop(edge, None)
        if mode == _DEP_ACC and e is not None:
            return x + e
        return x

    def _encode_edge(self, edge: Tuple[int, int], x: np.ndarray, wire_t,
                     mode: int):
        """Per-edge variant of ``_encode_row`` for an overridden edge:
        ``(payload, estimate, fold_mode, wire_codec)`` against the edge's
        own estimator state. ``wire_codec`` is what actually rides the
        deposit header — normally the override itself, but a rebase send
        (see set_edge_codec) ships through the codec's state fallback."""
        codec = self._edge_codec[edge]
        acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
        fold_mode = mode
        ref = None
        if codec.error_feedback and mode == _DEP_PUT:
            ref = self._ef_edge_ref.get(edge)
            if ref is None:
                # REBASE: resync the receiver slot with a full overwrite
                # through the non-EF state codec, then track its decode as
                # the shared reference — the deltas that follow integrate
                # from exactly what the receiver folded.
                wire = _wire_codec.state_codec_for(codec)
                raw = np.ascontiguousarray(
                    x.astype(wire_t, copy=False)).reshape(-1)
                payload = wire.encode(raw)
                est = wire.decode(payload, wire_t, raw.size).astype(
                    acc_t, copy=False).reshape(self.row_shape)
                self._ef_edge_ref[edge] = est
                self._ef_edge_rows[edge] = x - est
                _metrics.counter("win.codec.edge_rebase").inc()
                _metrics.counter("win.codec.raw_bytes").inc(raw.nbytes)
                _metrics.counter("win.codec.wire_bytes").inc(payload.nbytes)
                return payload, est, _DEP_PUT, wire
            base = x - ref
            fold_mode = _DEP_ACC
        elif codec.error_feedback:
            base = x + self._edge_residual(edge)
        else:
            # non-EF codec: a leftover residual from a pre-switch EF codec
            # still folds into the next accumulate's base (mass carries);
            # its own quantization error keeps being tracked from then on
            # so numerator mass stays exact across the switch
            e = self._ef_edge_rows.get(edge) if mode == _DEP_ACC else None
            base = x if e is None else x + e
        raw = np.ascontiguousarray(
            base.astype(wire_t, copy=False)).reshape(-1)
        payload = codec.encode(raw)
        est = codec.decode(payload, wire_t, raw.size).astype(
            acc_t, copy=False).reshape(self.row_shape)
        if codec.error_feedback:
            if mode == _DEP_PUT:
                self._ef_edge_ref[edge] = ref + est
                self._ef_edge_rows[edge] = x - self._ef_edge_ref[edge]
            else:
                self._ef_edge_rows[edge] = base - est
            _metrics.gauge("win.codec.residual_norm").set(
                self.ef_residual_norm())
        elif mode == _DEP_ACC and edge in self._ef_edge_rows:
            self._ef_edge_rows[edge] = base - est
        _metrics.counter("win.codec.raw_bytes").inc(raw.nbytes)
        _metrics.counter("win.codec.wire_bytes").inc(payload.nbytes)
        _metrics.gauge("win.codec.ratio").set(
            raw.nbytes / payload.nbytes if payload.nbytes else 0.0)
        return payload, est, fold_mode, codec

    def _encode_row(self, src: int, x: np.ndarray, wire_t, mode: int):
        """Encode one source row for the wire:
        ``(payload, estimate, fold_mode)``.

        The codec encodes each row ONCE per op — the same payload feeds
        every out-edge (weights move receiver-side via the extension
        header) and the same decoded ``estimate`` feeds the local folds,
        so a single-controller hosted window and a cross-controller wire
        produce identical numerics.

        Error-feedback codecs split by op mode (docs/compression.md):

        * **put** (overwrite semantics) uses the CHOCO-SGD construction —
          ship ``C(x - x̂)`` against a sender-tracked estimate ``x̂``
          that advances by exactly the decoded increment, and fold it
          ADDITIVELY (``fold_mode`` flips to accumulate), so the mailbox
          slot integrates to the same ``x̂`` both ends agree on. A raw
          ``C(x)`` overwrite would zero the unsent coordinates every
          step — the scheme that does NOT converge for parameter gossip.
        * **accumulate** (push-sum mass) uses classic EF-SGD — ship
          ``C(x + e)``, keep ``e = (x + e) - est``: dropped numerator
          mass is delayed to later deposits, never lost, while the
          associated-p channel ships exact in the header.
        """
        codec = self.codec
        acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
        fold_mode = mode
        if codec.error_feedback and mode == _DEP_PUT:
            ref = self._ef_ref.get(src)
            if ref is None:
                ref = self._ef_ref[src] = np.zeros(self.row_shape, acc_t)
            base = x - ref
            fold_mode = _DEP_ACC
        elif codec.error_feedback:
            base = x + self.ef_residual(src)
        else:
            base = x
        raw = np.ascontiguousarray(base.astype(wire_t, copy=False)).reshape(-1)
        payload = codec.encode(raw)
        est = codec.decode(payload, wire_t, raw.size).astype(
            acc_t, copy=False).reshape(self.row_shape)
        if codec.error_feedback:
            if mode == _DEP_PUT:
                self._ef_ref[src] = ref + est
                self._ef_rows[src] = x - self._ef_ref[src]  # unsent gap
            else:
                self._ef_rows[src] = base - est
            _metrics.gauge("win.codec.residual_norm").set(
                self.ef_residual_norm())
        _metrics.counter("win.codec.raw_bytes").inc(raw.nbytes)
        _metrics.counter("win.codec.wire_bytes").inc(payload.nbytes)
        _metrics.gauge("win.codec.ratio").set(
            raw.nbytes / payload.nbytes if payload.nbytes else 0.0)
        return payload, est, fold_mode

    def _start_deposit(self, pair, rec, expect: int) -> Optional[_PendingDeposit]:
        """Parse a deposit's header record into reassembly state.

        Put-mode deposits stream straight into the mailbox slot: the wire
        dtype always equals the mail dtype (floating windows ship their own
        dtype; integer windows' mailboxes ARE the f32 acc dtype), so a put
        is a pure byte copy with no accumulation pass. Accumulate-mode
        stages into a scratch buffer and folds once complete.

        Codec deposits (mode byte's high nibble non-zero): the encoded
        payload's size differs from the row size — the extension header
        carries it — and both modes must stage (the payload is a codec
        record, not slot bytes); the fold decodes at ``_finish_deposit``.
        ``expect`` is the raw-wire payload byte count (row size in the
        wire dtype), used by legacy deposits."""
        seq = int.from_bytes(rec[:_DEP_TAG], "little") >> 24
        raw_mode, has_p, pc, nchunks = struct.unpack_from(
            "<BBdI", rec, _DEP_TAG)
        codec_id = raw_mode >> _DEP_CODEC_SHIFT
        mode = raw_mode & _DEP_MODE_MASK
        wt = 1.0
        hdr_end = _DEP_TAG + _DEP_HDR
        if codec_id:
            wt, expect = struct.unpack_from("<dQ", rec, hdr_end)
            hdr_end += _DEP_EXT
        shard = -1
        if raw_mode & _DEP_SHARD_FLAG:
            shard, = struct.unpack_from("<i", rec, hdr_end)
            hdr_end += _DEP_SHARD_EXT
        # Rotation-drift guard: a shard-carrying deposit whose index is
        # not THIS owner's active shard holds a different subspace's
        # coordinates — folding it would mix misaligned coordinates. The
        # value is discarded (the slot keeps its last same-shard content,
        # i.e. one-rotation-stale — the per-shard analog of the hosted
        # plane's usual staleness). Accumulate-mode p mass still folds so
        # push-sum conservation survives drift; put-mode p is dropped
        # with the value so the slot's (value, p) pair stays coherent
        # (see _finish_deposit). win.shard_stale_drops counts it:
        # persistent growth means a controller's comm-round counter
        # drifted (see straggler detection, docs/metrics.md).
        discard = shard >= 0 and shard != self.active_shard
        if codec_id or discard:
            staging = np.empty(expect, np.uint8)
            target = staging
        elif mode == _DEP_PUT:
            target = self._mail_rows[pair[0]][pair[1]].reshape(-1).view(
                np.uint8)
            staging = None
        else:
            staging = np.empty(expect, np.uint8)
            target = staging
        pend = _PendingDeposit(mode, has_p, pc, seq, nchunks, target,
                               staging, codec_id=codec_id, wt=wt,
                               expect=int(expect), shard=shard,
                               discard=discard)
        # compact single-record form: a header carrying payload inline
        body = rec[hdr_end:]
        if len(body):
            pend.target[:len(body)] = np.frombuffer(body, np.uint8)
            pend.hdr_len = pend.got = len(body)
        return pend

    def _place_chunk(self, pair, pend: "_PendingDeposit", idx: int,
                     body) -> None:
        """Place one continuation chunk at its deterministic offset.

        Striped senders fan a deposit's chunk records across the
        connection pool, so chunks may arrive in ANY order; the tag index
        pins each one's offset — every chunk except the last is exactly
        the sender's chunk size (learned from whichever non-last chunk
        arrives first), and the last chunk anchors to the tail. In-order
        single-stream arrival degenerates to the same math."""
        expect = pend.expect
        blen = len(body)
        off = -1
        bad = idx < 1 or idx > pend.nchunks or idx in pend.seen
        if not bad:
            if idx == pend.nchunks:
                off = expect - blen
            else:
                if pend.cap is None:
                    pend.cap = blen
                off = pend.hdr_len + (idx - 1) * pend.cap
                bad = blen != pend.cap
        if bad or off < 0 or off + blen > expect:
            raise RuntimeError(
                f"window '{self.name}': deposit chunk {idx} for (rank, "
                f"slot) {pair} of {blen} bytes does not fit the expected "
                f"{expect}-byte payload — wire corruption or a mismatched "
                "window shape across controllers")
        if blen:
            pend.target[off:off + blen] = np.frombuffer(body, np.uint8)
            pend.got += blen
        pend.seen.add(idx)

    def _finish_deposit(self, pair, pend: _PendingDeposit) -> None:
        # close the origin's flow arrow: same id the sender emitted
        # (the 39-bit (origin << 32 | counter) tag sequence)
        timeline_flow_finish(_FLOW_DEPOSIT, pend.seq)
        _metrics.counter("win.deposits_drained").inc()
        fl = _flight.recorder()
        fl.rec(_flight.FLOW_F,
               fl.intern(f"drain.{(pend.seq >> 32) & 0x7F}"),
               pend.got, pend.seq)
        if pend.discard:
            # rotation drift (see _start_deposit): accumulate-mode still
            # folds the exact p mass — push-sum conservation must survive
            # drift even when the value cannot. Put-mode drops the WHOLE
            # (value, p) pair: set_p_mail against the slot's retained
            # previous-rotation value would leave a torn pair (stale
            # value, fresh weight) that biases the combine, whereas
            # keeping both halves from the last same-shard deposit is
            # merely one rotation stale and self-consistent.
            _metrics.counter("win.shard_stale_drops").inc()
            if pend.has_p and pend.mode == _DEP_ACC:
                self.host.add_p_mail(pair[0], pair[1], pend.pc)
            return
        if pend.codec_id:
            # compressed deposit: decode the self-describing payload back
            # to a full wire-dtype row, apply the edge weight the sender
            # moved receiver-side (one encode per source row feeds every
            # out-edge), and fold — put OR accumulate — through the usual
            # acc-dtype discipline (docs/compression.md)
            wire_t = _win_wire_dtype(self.mail_dtype)
            acc_t = np.dtype(_win_acc_dtype(self.mail_dtype))
            n = int(np.prod(self.row_shape, dtype=np.int64))
            codec_obj = _wire_codec.by_id(pend.codec_id)
            # error-feedback put deposits are CHOCO deltas: integrate them
            # (the slot tracks the sender's x̂) instead of overwriting
            fold_mode = _DEP_ACC if (codec_obj.error_feedback
                                     and pend.mode == _DEP_PUT) \
                else pend.mode
            _metrics.counter("win.codec.wire_bytes_in").inc(pend.got)
            slot = self._mail_rows[pair[0]][pair[1]]
            with fl.span("win.fold", a=pend.got):
                if fold_mode == _DEP_PUT and slot.dtype == np.float32:
                    # decode STRAIGHT into the mailbox slot with the edge
                    # weight folded into the per-block scales: two passes
                    # over the row instead of decode + weight + copy
                    codec_obj.decode(pend.staging, np.float32, n,
                                     scale_mul=pend.wt,
                                     out=slot.reshape(-1))
                else:
                    flat = codec_obj.decode(pend.staging, wire_t, n,
                                            scale_mul=pend.wt)
                    contrib = flat.astype(acc_t, copy=False).reshape(
                        self.row_shape)
                    self._fold_record(pair[0], pair[1], fold_mode, contrib)
        elif pend.mode == _DEP_ACC:
            wire_t = _win_wire_dtype(self.mail_dtype)
            contrib = pend.staging.view(wire_t).reshape(self.row_shape)
            with fl.span("win.fold", a=pend.got):
                self._fold_record(pair[0], pair[1], _DEP_ACC, contrib)
        if pend.has_p:
            if pend.mode == _DEP_ACC:
                self.host.add_p_mail(pair[0], pair[1], pend.pc)
            else:
                self.host.set_p_mail(pair[0], pair[1], pend.pc)

    def _drain_deposits(self, strict: bool = False) -> None:
        """Take pending server deposits for every owned rank and fold them
        in deposit order. Called under state_mu (win_update).

        One pipelined multi-take covers every (rank, slot) mailbox per
        round (latency no longer scales with owned x d_max); rounds repeat
        while anything arrived, since the server bounds each key's reply
        (kMaxTakeReply) and chunked deposits may span rounds. A deposit
        whose continuation chunks are still in flight from a concurrently
        writing origin is held as partial state and completed by a bounded
        re-poll — never folded torn.

        **Pipelined fold** (r6): after a round that produced records, the
        NEXT round's take is issued immediately on a prefetch thread, so
        the server-side gather + socket stream of round i+1 overlaps the
        fold of round i (the fold-vs-stream split is measured by
        scripts/win_microbench.py's fold_vs_stream probe). Each record is
        a zero-copy view into the native reply buffer and is copied
        exactly once — into the mailbox slot itself for put-mode deposits
        (wire dtype == mail dtype, no accumulation pass) or an acc-mode
        staging buffer.

        **Striped reassembly + orphan discard** (r7): every record carries
        the server-prefixed deposit tag. Chunks place at their tag-index
        offset, so a striped origin's out-of-order arrivals (chunk records
        fanned across the connection pool) reassemble exactly; pendings
        are keyed per (mailbox key, seq) so interleaved deposits from
        independent origin namespaces coexist. Orphans — the tail a
        win_free/win_fence clear raced past — are recognized two ways:
        a chunk with no drained header (senders append the header before
        any chunk, so a missing header was eaten, not late), and a pending
        superseded by a newer deposit counter in its own origin namespace
        (deposits are fully appended before their successor starts).

        ``strict`` (caller holds the rank mutexes AND the job opted in via
        ``BLUEFOG_WIN_STRICT=1``): verify the write/read exclusion actually
        held — every slot with a pending deposit must show version >= 1,
        because origins bump BEFORE depositing inside their mutex-held
        region (_hosted_exchange) and the owner resets only inside its own.
        A version-0 deposit means some participant skipped
        ``require_mutex``; raising turns the silent one-update-late consume
        into a diagnosable error (reference: the version-window protocol,
        mpi_controller.cc:1281-1393, whose strict mode is MPI_Win_lock
        exclusion). Opt-in because mixed usage is legal per the reference:
        a mutex-holding updater coexisting with advisory non-mutex origins
        must not crash (the module header documents that advisory race)."""
        strict = strict and os.environ.get("BLUEFOG_WIN_STRICT") == "1"
        cl = _cp.client()
        pairs = [(r, k) for r in self.owned
                 for k in range(self.layout.d_max)]
        expect = int(np.prod(self.row_shape, dtype=np.int64)) * \
            _win_wire_dtype(self.mail_dtype).itemsize
        touched: set = set()
        # Striped origins fan one deposit's chunk records across the
        # connection pool, so records of ADJACENT deposits (and of
        # interleaved origins, each in its own tag namespace) can arrive
        # interleaved: pendings are keyed per (mailbox key, seq).
        partial: Dict[Tuple[int, int], Dict[int, _PendingDeposit]] = {}
        orphans = 0
        drain_timeout = float(os.environ.get(
            "BLUEFOG_WIN_DRAIN_TIMEOUT", "60"))

        def sweep(poll_pairs, pooled=True):
            poll_names = [self._dep_key(r, k) for r, k in poll_pairs]
            return (_Prefetch(lambda: cl.take_bytes_many_views(
                        poll_names, pooled=pooled)),
                    poll_pairs)

        drained_records = 0
        drained_bytes = 0
        # step-attribution span: the socket-sweep + reassembly leg of
        # the drain; the numpy folds inside carve themselves out via
        # nested win.fold spans (scripts/step_attribution.py subtracts
        # the overlap so the phase buckets stay disjoint)
        _fl = _flight.recorder()
        _fl.begin("win.drain")
        try:
            fetch, fetch_pairs = sweep(pairs)
            while True:
                batches, owner = fetch.result()
                cur_pairs, fetch = fetch_pairs, None
                got = any(batches)
                if got:
                    drained_records += sum(len(recs) for recs in batches)
                    drained_bytes += sum(
                        len(r) for recs in batches for r in recs)
                    # Progress: sweep everything once more, streamed WHILE the
                    # records below fold (an empty extra sweep costs one RTT).
                    # Pool the next sweep only when THIS round hauled bulk
                    # bytes: fat backlogs stripe across the connection pool,
                    # while trickle rounds stay on one pipelined connection —
                    # a pooled sweep's extra round-trips would otherwise let a
                    # fast depositor outrun the drain loop indefinitely.
                    round_bytes = sum(len(r) for recs in batches for r in recs)
                    fetch, fetch_pairs = sweep(
                        pairs,
                        pooled=round_bytes >= getattr(
                            cl, "_stripe_min", 1 << 22))
                try:
                    for pair, records in zip(cur_pairs, batches):
                        if not records:
                            continue
                        touched.add(pair)
                        pend_map = partial.get(pair)
                        if pend_map is None:
                            pend_map = partial[pair] = {}
                        # newest deposit counter seen per origin namespace this
                        # round — anything older it supersedes is orphaned
                        ns_max: Dict[int, int] = {}
                        for rec in records:
                            tag = int.from_bytes(rec[:_DEP_TAG], "little")
                            seq, idx = tag >> 24, tag & 0xFFFFFF
                            ns, ctr = seq >> 32, seq & 0xFFFFFFFF
                            prev = ns_max.get(ns)
                            if prev is None or _seq_newer(ctr, prev):
                                ns_max[ns] = ctr
                            if idx == 0:
                                if seq in pend_map:
                                    # duplicate header: impossible from the
                                    # clear race; belt-and-braces for a
                                    # corrupted peer
                                    orphans += 1
                                pend = pend_map[seq] = self._start_deposit(
                                    pair, rec, expect)
                            else:
                                pend = pend_map.get(seq)
                                if pend is None:
                                    # Orphaned continuation: every sender
                                    # appends a deposit's header before any of
                                    # its chunks reach the server (the striped
                                    # append's phase split pins this), so a
                                    # chunk whose header we never drained means
                                    # a win_free/win_fence clear ate the
                                    # deposit's prefix — discard the tail.
                                    orphans += 1
                                    continue
                                self._place_chunk(pair, pend,
                                                  idx, rec[_DEP_TAG:])
                            if pend.got == pend.expect:
                                self._finish_deposit(pair, pend)
                                del pend_map[seq]
                        # GC: per-origin deposit counters are monotonic and a
                        # deposit is fully appended before its successor starts,
                        # so a pending superseded by a NEWER counter in its own
                        # namespace can never complete — its missing records
                        # were consumed by a concurrent clear.
                        for seq_o in list(pend_map):
                            m = ns_max.get(seq_o >> 32)
                            if m is not None and _seq_newer(m, seq_o & 0xFFFFFFFF):
                                del pend_map[seq_o]
                                orphans += 1
                        if not pend_map:
                            del partial[pair]
                finally:
                    owner.close()
                if not partial:
                    if not got:
                        break  # no prefetch outstanding (got False issued none)
                    continue
                # Per-PARTIAL deadline, anchored when that chunk sequence first
                # appeared: progress on unrelated keys must not keep a torn
                # deposit alive forever (healthy gossip traffic would otherwise
                # reset a shared clock on every round).
                now = time.monotonic()
                stale = sorted({p for p, pmap in partial.items()
                                for pend in pmap.values()
                                if now - pend.t0 > drain_timeout})
                if stale:
                    raise RuntimeError(
                        f"window '{self.name}': deposit chunk sequence for "
                        f"(rank, slot) {stale} never completed within "
                        f"{drain_timeout:.0f}s — the origin died mid-deposit "
                        "(BLUEFOG_WIN_DRAIN_TIMEOUT)")
                if not got:
                    # only the keys holding partial chunk sequences can produce
                    # the awaited continuations; don't sweep owned x d_max keys
                    # 200x/s while waiting on one slow origin
                    time.sleep(0.005)
                    fetch, fetch_pairs = sweep(sorted(partial), pooled=False)
        finally:
            _fl.end("win.drain", a=drained_bytes)
        if drained_records:
            _metrics.counter("win.drain_records").inc(drained_records)
            _metrics.counter("win.drain_bytes").inc(drained_bytes)
            # counter track next to the WIN_UPDATE span that did the drain
            timeline_counter("win.drained_records", drained_records)
        if orphans:
            _metrics.counter("win.drain_orphans").inc(orphans)
            logger.debug(
                "window '%s': discarded %d orphaned deposit chunk(s) left "
                "by a concurrent clear", self.name, orphans)
        if strict and touched:
            stale = sorted(touched)
            vers = self.host.get_versions(stale)
            bad = [pair for pair, v in zip(stale, vers) if v == 0]
            if bad:
                raise RuntimeError(
                    f"window '{self.name}': deposits consumed at version 0 "
                    f"for (rank, slot) {bad} — an origin wrote without "
                    "require_mutex while this update held the rank mutex; "
                    "strict window consistency requires every participant "
                    "to pass require_mutex=True")

    def close(self, aligned: bool = True) -> None:
        """Release hosted-plane server state (win_free).

        Like MPI_Win_free, freeing is collective: the first barrier aligns
        every controller past its last data op on this window, then each
        owner discards its ranks' pending deposits and published tensors so
        a later window under the same name starts clean; the second barrier
        keeps any controller from re-creating the name mid-cleanup.

        ``aligned=False`` (the shutdown path) skips both barriers: peers may
        already be gone, and a barrier would hang teardown — the one-sided
        server cleanup (drain + clear published bytes) still runs so an
        externally shared server does not accumulate dead windows' memory."""
        if not self.hosted:
            return
        if aligned:
            self.host.flush()
        cl = _cp.client()
        names = [self._dep_key(r, k) for r in self.owned
                 for k in range(self.layout.d_max)]
        while any(cl.take_bytes_many(names)):
            pass
        cl.put_bytes_many([self._self_key(r) for r in self.owned],
                          [b""] * len(self.owned))
        if aligned:
            self.host.flush()

    # -- elastic rejoin support (hosted plane; ISSUE r9) -------------------

    def read_published_row(self, rank: int):
        """One rank's published window tensor, or None when absent or
        mis-sized (its controller never published, or is itself dead and
        its slot was cleared). The rejoin state transfer reads a donor's
        row through this — the same striped get_bytes transport win_get
        rides, reused as-is. Under a state codec the adopted row is the
        donor's quantized copy (bounded per-block error —
        docs/compression.md documents the rejoin tradeoff)."""
        raw = _cp.client().get_bytes(self._self_key(rank))
        try:
            return self._parse_published(rank, raw).copy()
        except RuntimeError:
            return None

    def install_row(self, rank: int, row) -> None:
        """Owner-write one OWNED rank's window row and publish it (the
        rejoiner installing transferred state; also the donor's half after
        a push-sum mass split)."""
        if rank not in self.owned:
            raise ValueError(f"install_row: rank {rank} is not owned here")
        with self.state_mu:
            self._rows[rank] = np.ascontiguousarray(row).astype(
                self.dtype, copy=False).copy()
            self._publish_selves([rank])

    # -- per-edge plane planner (hybrid gossip; ISSUE r13) -----------------

    def plane_partition(self, dead=frozenset(), epoch=None):
        """The planner's per-edge plane split for the current membership,
        or None when no planner is active (collective plane, forced-hosted
        plane, or a pre-``auto`` legacy config). Cached keyed on
        (edge set, dead set, membership epoch) inside the planner, so a
        gossip step pays a dict lookup, and r9's epoch fences are exactly
        the re-plan trigger."""
        if self._planner is None:
            return None
        if epoch is None:
            from ..runtime.heartbeat import membership_epoch

            epoch = membership_epoch()
        before = self._planner.rebuilds
        part = self._planner.partition(frozenset(dead), epoch)
        if self._planner.rebuilds != before:
            _metrics.counter("win.plan_rebuilds").inc()
            _metrics.gauge("win.compiled_edges").set(len(part.compiled))
            _metrics.gauge("win.hosted_edges").set(len(part.hosted))
        return part

    # -- compiled programs -------------------------------------------------

    def _exchange_fn(self, accumulate: bool, donate_source: bool = False,
                     identity_self: bool = False):
        """One-program put/get/accumulate: ppermute per shift + slot write.

        The mailbox carries one extra SCRATCH slot (index ``d_max``) so the
        put path can be pure write-only dynamic updates: an inactive edge
        redirects its write to the scratch slot instead of select-blending
        against the current slot value. Measured on the CPU mesh, any read
        of the donated mailbox inside the program (a ``jnp.where`` against
        ``cur``, a static-slice add) forces XLA into a defensive full-buffer
        copy per shift — 3-4x the whole op's cost at optimizer scale — while
        write-only updates alias in place even with a traced slot index.
        Accumulate must read the current slot by definition; it keeps the
        read-add-write per shift (and still benefits from the scratch
        redirect replacing the select).

        ``identity_self``: compile-time specialization for the all-ones
        self-weight the window optimizers pass on every put — the new self
        value IS the input, so the program skips a full window-sized
        multiply + materialize (with ``donate_source`` it aliases
        outright). ``donate_source``: the caller relinquishes the input
        buffer (the optimizer's packed fusion buffer is dead after the
        put), letting XLA reuse it instead of allocating a fresh self
        tensor.
        """
        # Quantization codecs apply to the compiled plane through the
        # mail-dtype blend (the value each edge materializes): the moved
        # payload rides the same int8/fp8 grid the hosted wire ships, so a
        # hybrid partition's two planes agree numerically. Top-k has no
        # dense-exchange analog (blend id 0 = exact legacy program).
        blend = self.codec.cid if self.codec is not None and \
            self.codec.cid in (_wire_codec.CODEC_INT8,
                               _wire_codec.CODEC_FP8) else 0
        key = ("xchg", accumulate, donate_source, identity_self, blend)
        fn = self._exchange_cache.get(key)
        if fn is not None:
            return fn
        st = _global_state()
        lay = self.layout
        n, shifts = lay.n, lay.shifts
        d_max = lay.d_max
        slot_c = np.asarray(lay.slot)  # [S, n] compile-time const

        def per_rank(x, mail, w, active, self_w):
            me = lax.axis_index("rank")
            xb = x[0]
            mb = mail[0]  # [d_max + 1, ...]; row d_max is scratch
            acc_t = _win_acc_dtype(xb.dtype)
            for si, s in enumerate(shifts):
                perm = [(i, (i + s) % n) for i in range(n)]
                moved = lax.ppermute(xb, "rank", perm)  # from (me - s) % n
                if blend:
                    moved = _wire_codec.quantize_blend(moved, blend)
                ak = active[si, me]
                # effective weight carries the active mask: an inactive
                # shift's write is redirected to the scratch slot AND its
                # payload is zeroed, so the scratch row stays finite and
                # win_update can contract the full buffer with a zero-padded
                # weight vector instead of slicing the scratch off (a partial
                # read would force the defensive copy documented above)
                wk = (w[si, me] * ak).astype(acc_t)
                k = jnp.where(ak > 0, jnp.asarray(slot_c)[si, me], d_max)
                contrib = moved.astype(acc_t) * wk
                if accumulate:
                    # accumulate in acc_t: bf16 mailboxes would otherwise
                    # round small contributions away (256 + 0.5 -> 256)
                    cur = lax.dynamic_index_in_dim(mb, k, axis=0,
                                                   keepdims=False)
                    val = (cur.astype(acc_t) + contrib).astype(mb.dtype)
                else:
                    val = contrib.astype(mb.dtype)
                mb = lax.dynamic_update_index_in_dim(mb, val, k, axis=0)
            if identity_self:
                new_self = xb
            else:
                new_self = (xb.astype(acc_t)
                            * self_w[me].astype(acc_t)).astype(xb.dtype)
            return new_self[None], mb[None]

        mapped = shard_map(
            per_rank,
            mesh=st.mesh,
            in_specs=(P("rank"), P("rank"), P(), P(), P()),
            out_specs=(P("rank"), P("rank")),
        )
        # Donate the mailbox: every caller rebinds win.mail to the output,
        # and without donation each per-shift dynamic_update materializes a
        # full mailbox copy (d_max x window bytes x shifts of pure memcpy —
        # the dominant cost of a collective-plane win_put at optimizer
        # scale). With donation XLA updates the buffer in place.
        donate = (0, 1) if donate_source else (1,)
        fn = jax.jit(mapped, donate_argnums=donate)
        self._exchange_cache[key] = fn
        return fn

    def _update_fn(self, reset: bool = False):
        """One-program combine: out = sw*self + nw . mail, + slot reset.

        Specialized on ``reset``: the no-reset variant returns the mailbox
        STRUCTURALLY unchanged, which — with the mailbox donated — lets XLA
        alias the output to the input (zero mailbox traffic) instead of
        multiplying every slot by a traced all-ones keep mask.
        """
        key = ("upd", reset)
        fn = self._update_cache.get(key)
        if fn is not None:
            return fn
        st = _global_state()

        def per_rank(self_v, mail, sw, nw, reset_mask):
            me = lax.axis_index("rank")
            mb = mail[0]          # [d_max + 1, ...]; row d_max is scratch
            sv = self_v[0]
            acc_t = _win_acc_dtype(sv.dtype)
            # Contract the FULL buffer with a zero-padded weight vector: the
            # scratch row is guaranteed finite (_exchange_fn zeroes inactive
            # payloads), and slicing it off ([:d_max]) would be a partial
            # read of the donated buffer — the defensive-copy pathology
            # _exchange_fn documents.
            w_me = jnp.concatenate(
                [nw[me], jnp.zeros((1,), nw.dtype)]).astype(acc_t)
            combined = sw[me].astype(acc_t) * sv.astype(acc_t) + jnp.tensordot(
                w_me, mb.astype(acc_t), axes=(0, 0))
            if reset:
                keep = jnp.concatenate(
                    [1.0 - reset_mask[me], jnp.ones((1,), reset_mask.dtype)]
                ).reshape((mb.shape[0],) + (1,) * (mb.ndim - 1))
                mail_new = (mb.astype(acc_t) * keep).astype(mb.dtype)
            else:
                mail_new = mb
            return combined.astype(sv.dtype)[None], mail_new[None]

        mapped = shard_map(
            per_rank,
            mesh=st.mesh,
            in_specs=(P("rank"), P("rank"), P(), P(), P()),
            out_specs=(P("rank"), P("rank")),
        )
        fn = jax.jit(mapped, donate_argnums=(1,))
        self._update_cache[key] = fn
        return fn


# ---------------------------------------------------------------------------
# Hybrid gossip: the compiled partition's fused program (ISSUE r13)
# ---------------------------------------------------------------------------
#
# One gossip step over a hybrid window splits its frozen edge set by the
# planner's verdict (Window.plane_partition): the COMPILED partition runs as
# ONE fused shard_map/ppermute program below — the in-neighbor exchange idiom
# of ops/neighbors.py:_gather_exchange_fn, with the mailbox-slot blend and
# weighted combine of _exchange_fn/_update_fn inlined behind it — while the
# HOSTED residual keeps the mailbox deposit/drain semantics via
# _residual_update. The fused program replicates the collective plane's op
# sequence exactly (same per-shift contributions cast through the mail
# dtype, same slot-ordered tensordot combine, same self term), so an
# all-compiled partition is bit-exact against the pure collective plane —
# the equivalence tests/test_win_planes.py pins.
#
# The program runs on the controller's LOCAL mesh (its owned devices): a
# compiled edge is mesh-local by planner construction, so dispatch is
# unilateral — no cross-controller lockstep, the asynchrony the hosted plane
# exists for survives. Static inputs (perms, slots) come from the partition;
# weights stay traced, so healed re-weights never re-jit — only a partition
# change does (the BLUEFOG_WIN_PLAN_MIN_MB floor exists because that re-jit
# is the cost hosted latency is traded against).


def _hybrid_meta(win: Window, part) -> dict:
    """Static tables for one partition's fused program: the local mesh,
    global→local index map, per-shift local permutation lists (naming ONLY
    live compiled edges — no compiled program may name a dead rank), and
    the local slot table."""
    key = ("meta", part.key)
    meta = win._hybrid_cache.get(key)
    if meta is not None:
        return meta
    st = _global_state()
    owned = win.owned
    k = len(owned)
    li = {r: i for i, r in enumerate(owned)}
    lay = win.layout
    by_shift: Dict[int, list] = {}
    for (src, dst) in sorted(part.compiled):
        by_shift.setdefault((dst - src) % lay.n, []).append(
            (li[src], li[dst]))
    shifts = tuple(sorted(by_shift))
    S = max(len(shifts), 1)
    slot = np.zeros((S, k), np.int32)
    perms = []
    for si, s in enumerate(shifts):
        perms.append(tuple(sorted(by_shift[s])))
        for (ls, ld) in by_shift[s]:
            slot[si, ld] = lay.slot_of[owned[ld]][owned[ls]]
    if k == st.size:
        mesh = st.mesh
    else:
        if win._local_mesh is None:
            win._local_mesh = Mesh(
                np.array([st.devices[r] for r in owned]), ("rank",))
        mesh = win._local_mesh
    meta = {"mesh": mesh, "li": li, "shifts": shifts,
            "perms": tuple(perms), "slot": slot, "k": k}
    if len(win._hybrid_cache) > 64:
        win._hybrid_cache.clear()
    win._hybrid_cache[key] = meta
    return meta


def _hybrid_fn(win: Window, meta: dict, accumulate: bool):
    """The fused compiled-partition program, cached per (mode, perms).

    Body = _exchange_fn's per-shift mailbox blend over a FRESH zero mailbox
    + _update_fn's slot-ordered weighted combine, chained in one jit. The
    intermediate mail values round-trip through the mail dtype exactly as
    the two-program collective pair materializes them, which is what makes
    the all-compiled case bit-exact against that plane.
    """
    blend = win.codec.cid if win.codec is not None and \
        win.codec.cid in (_wire_codec.CODEC_INT8,
                          _wire_codec.CODEC_FP8) else 0
    key = ("fn", accumulate, meta["perms"], meta["k"], blend)
    fn = win._hybrid_cache.get(key)
    if fn is not None:
        return fn
    d_max = win.layout.d_max
    mail_dtype = win.mail_dtype
    slot_c = np.asarray(meta["slot"])
    perms = meta["perms"]

    def per_rank(x, w, active, sw_put, sw_upd, nw):
        me = lax.axis_index("rank")
        xb = x[0]
        acc_t = _win_acc_dtype(xb.dtype)
        mb = jnp.zeros((d_max + 1,) + xb.shape, mail_dtype)
        for si in range(len(perms)):
            moved = lax.ppermute(xb, "rank", list(perms[si]))
            if blend:
                # the compiled partition's mail-dtype blend rides the same
                # quantized grid as the hosted wire (docs/compression.md)
                moved = _wire_codec.quantize_blend(moved, blend)
            ak = active[si, me]
            wk = (w[si, me] * ak).astype(acc_t)
            # inactive (no compiled edge on this shift for me): redirect the
            # zero payload to the scratch row so real slots are write-only,
            # the same discipline as _exchange_fn
            kk = jnp.where(ak > 0, jnp.asarray(slot_c)[si, me], d_max)
            contrib = moved.astype(acc_t) * wk
            if accumulate:
                cur = lax.dynamic_index_in_dim(mb, kk, axis=0,
                                               keepdims=False)
                val = (cur.astype(acc_t) + contrib).astype(mb.dtype)
            else:
                val = contrib.astype(mb.dtype)
            mb = lax.dynamic_update_index_in_dim(mb, val, kk, axis=0)
        new_self = (xb.astype(acc_t)
                    * sw_put[me].astype(acc_t)).astype(xb.dtype)
        w_me = jnp.concatenate(
            [nw[me], jnp.zeros((1,), nw.dtype)]).astype(acc_t)
        combined = sw_upd[me].astype(acc_t) * new_self.astype(acc_t) + \
            jnp.tensordot(w_me, mb.astype(acc_t), axes=(0, 0))
        return combined.astype(xb.dtype)[None]

    mapped = shard_map(
        per_rank,
        mesh=meta["mesh"],
        in_specs=(P("rank"), P(), P(), P(), P(), P()),
        out_specs=P("rank"),
    )
    fn = jax.jit(mapped)
    win._hybrid_cache[key] = fn
    return fn


def _local_view(win: Window, meta: dict, x):
    """The rank-stacked buffer's owned rows as a local-mesh array (the
    identity when this controller owns the whole mesh)."""
    if meta["k"] == win.size:
        return x
    shards = {s.index[0].start or 0: s.data for s in x.addressable_shards}
    sh = NamedSharding(meta["mesh"], P("rank"))
    return jax.make_array_from_single_device_arrays(
        (meta["k"],) + tuple(x.shape[1:]), sh,
        [shards[r] for r in win.owned])


def _globalize(win: Window, meta: dict, local):
    """Local-mesh combined rows back to the global rank-stacked array
    (metadata-only: each controller contributes its addressable shards)."""
    st = _global_state()
    if meta["k"] == st.size:
        return local
    sh = NamedSharding(st.mesh, P("rank"))
    shards = sorted(((s.index[0].start or 0, s.data)
                     for s in local.addressable_shards), key=lambda p: p[0])
    # local row i is global rank owned[i]; reorder by global rank
    per_rank = [d for _, d in shards]
    return jax.make_array_from_single_device_arrays(
        (st.size,) + tuple(local.shape[1:]), sh, per_rank)


def _run_compiled_partition(win: Window, x, part, put_table, sw_put,
                            sw_upd, nw_table, accumulate: bool = False):
    """Run the compiled partition's fused program over the rank-stacked
    buffer ``x``. Weight inputs are global-rank keyed (the same tables the
    hosted ops take); only compiled edges contribute. Returns the combined
    per-owned-rank rows as a local-mesh device array (``_globalize`` lifts
    it back)."""
    meta = _hybrid_meta(win, part)
    li, k = meta["li"], meta["k"]
    lay = win.layout
    S = max(len(meta["perms"]), 1)
    w = np.zeros((S, k), np.float32)
    active = np.zeros((S, k), np.float32)
    shift_index = {s: i for i, s in enumerate(meta["shifts"])}
    nw_arr = np.zeros((k, lay.d_max), np.float32)
    for (src, dst) in part.compiled:
        wt = put_table.get(src, {}).get(dst)
        uw = nw_table.get(dst, {}).get(src)
        if wt is None or uw is None:
            continue  # edge dropped by the (healed) weight tables
        si = shift_index[(dst - src) % lay.n]
        w[si, li[dst]] = wt
        active[si, li[dst]] = 1.0
        nw_arr[li[dst], lay.slot_of[dst][src]] = uw
    sw_put_arr = np.asarray([sw_put[r] for r in win.owned], np.float32)
    sw_upd_arr = np.asarray([sw_upd[r] for r in win.owned], np.float32)
    fn = _hybrid_fn(win, meta, accumulate)
    fl = _flight.recorder()
    with timeline_context(win.name, "WIN_COMPILED"), \
            fl.span("win.compiled"):
        out = fn(_local_view(win, meta, x), w, active, sw_put_arr,
                 sw_upd_arr, nw_arr)
    return out, meta


def _combine_with_residual(win: Window, meta: dict, comp, rows):
    """comp (local-mesh device rows) + the hosted residual's folded rows
    (numpy per owned rank, or None when the residual contributed nothing).
    Adding exactly 0.0 would still be bit-transparent, but skipping the add
    keeps the all-compiled fast path a single program."""
    if rows is None:
        return comp
    stacked = np.stack([np.asarray(rows[r]) for r in win.owned])
    dev = jax.device_put(stacked.astype(np.dtype(comp.dtype), copy=False),
                         NamedSharding(meta["mesh"], P("rank")))
    return comp + dev


def _residual_update(win: Window, nw_table, reset: bool = False,
                     require_mutex: bool = True):
    """The hosted residual's combine leg: drain + fold pending deposits,
    then contract ONLY the residual in-edges' mailbox slots (no self term
    — the compiled program owns it). Returns ``(rows, p_sums)``: the
    per-owned-rank weighted residual contribution (numpy) and, when
    associated-p is on, the matching p-mailbox contraction. Window rows
    stay untouched (clone semantics) — the put leg's publish is the
    step's visible state."""
    st = _global_state()
    n = st.size
    lay = win.layout
    nw = np.zeros((n, lay.d_max), np.float32)
    read_mask = np.zeros((n, lay.d_max), np.float32)
    for r, wmap in nw_table.items():
        for src, wt in wmap.items():
            kslot = lay.slot_of[r][src]
            nw[r, kslot] = wt
            read_mask[r, kslot] = 1.0
    return _hosted_update(win, [0.0] * n, nw_table, nw, read_mask,
                          reset=reset, clone=True,
                          require_mutex=require_mutex, return_rows=True)


# Deposit record (hosted plane wire format):
#   i64 tag | u8 mode | u8 has_p | f64 p_contrib | u32 nchunks | payload chunk
# followed by nchunks-1 ``i64 tag | raw chunk`` continuation records on the
# same mailbox key. The tag — ``seq << 24 | record_index`` — is supplied to
# the server per record (kAppendBytesTagged) and prefixed server-side, so
# the drain can tell a deposit's first record (index 0, carries the header)
# from a continuation chunk STRUCTURALLY: after win_free/win_fence clears a
# mailbox mid-deposit, the orphaned continuation chunks that land afterwards
# are discarded by tag instead of being misparsed as headers (spurious "wire
# corruption" / 60 s drain timeouts — ADVICE r5 medium).
# Payload dtype is the WINDOW's own dtype for floating windows (VERDICT r4
# #1: acc-dtype deposits shipped 2x the bytes for bf16 windows; the
# reference's wire also carries the tensor's own dtype). Integer windows
# keep the f32 acc dtype: fractional edge weights make the weighted
# contribution non-integral, and truncating per-deposit would change the
# accumulate semantics the compiled plane defines. Chunking (size from
# BLUEFOG_MAX_WIN_SENT_LENGTH, reference mpi_controller.cc:41-46) bounds
# every control-plane message and lets a drain move in bounded rounds.
# Chunk contiguity per key is structural: a mailbox key (dst, slot) maps
# 1:1 to one source rank, whose controller serializes its deposits under
# the window state lock.
_DEP_PUT = 0
_DEP_ACC = 1
_DEP_HDR = struct.calcsize("<BBdI")
_DEP_TAG = 8  # server-prefixed i64 tag bytes per stored record
_DEFAULT_MAX_SENT = 16 << 20
# Compressed-wire extension (ISSUE r15, docs/compression.md): a codec id
# rides the HIGH NIBBLE of the header's mode byte (the legacy wire's mode
# byte is 0/1, so BLUEFOG_WIN_CODEC=none stays byte-identical — pinned).
# When the nibble is non-zero, an extension header follows the base one:
#   f64 edge weight | u64 encoded payload bytes
# The weight moves receiver-side because the codec encodes each source ROW
# once (one encode feeds every out-edge — and, for top-k, one
# error-feedback residual per row); the payload itself is the codec's
# self-describing record (ops/codec.py), so its length differs from the
# row size and the drain completes it by the header's byte count.
_DEP_MODE_MASK = 0x07
_DEP_CODEC_SHIFT = 4
_DEP_EXT = struct.calcsize("<dQ")
# Sharded-rotation extension (ISSUE r17, docs/sharded_windows.md): bit 3
# of the mode byte's low nibble flags a shard-carrying deposit; an i32
# shard index follows the base header (after the codec extension when
# both ride). The legacy wire never sets the bit (mode byte low nibble is
# 0/1 there), so unsharded windows stay byte-identical.
_DEP_SHARD_FLAG = 0x08
_DEP_SHARD_EXT = struct.calcsize("<i")
# Published-row ("exposed window") state-codec framing: raw rows have no
# header (the legacy format, length == row bytes); encoded rows carry
# u32 magic | u8 codec id | 3 reserved bytes, then the self-describing
# codec payload. Readers dispatch on length + magic (_parse_published).
_PUB_MAGIC = 0x43575642  # "BVWC"
_PUB_HDR = struct.calcsize("<IBBH")


def _deposit_tags(seq: int, nrec: int, origin: int = 0) -> List[int]:
    """Per-record int64 tags for one deposit: ``seq << 24 | record_index``.

    The 39-bit seq field namespaces a 32-bit per-origin deposit counter
    under a 7-bit origin id (``origin << 32 | counter``): the drain's
    supersession GC compares counters only within one origin's namespace,
    so interleaved writers (one per controller in the multi-origin stress
    shape) cannot orphan each other's in-flight deposits. The counter
    wraps modularly (uniqueness only matters between ADJACENT deposits on
    one key); 24 index bits cover rows up to ~1 PB at the 64 KiB chunk
    floor."""
    base = (((origin & 0x7F) << 32) | (seq & 0xFFFFFFFF)) << 24
    return [base | (i & 0xFFFFFF) for i in range(nrec)]


def _seq_newer(a: int, b: int) -> bool:
    """Modular 32-bit counter comparison: is ``a`` strictly newer than
    ``b``? (Wrap-safe for the per-origin deposit counters.)"""
    return a != b and ((a - b) & 0xFFFFFFFF) < (1 << 31)


class _Prefetch:
    """Run ``fn()`` on a worker thread; ``result()`` joins and re-raises.

    The drain/get pipelines use it to stream the NEXT server reply while
    the current one folds — ctypes releases the GIL inside the native
    call and numpy releases it for bulk copies, so the overlap is real."""

    __slots__ = ("_t", "_r", "_e")

    def __init__(self, fn) -> None:
        self._r = self._e = None

        def run():
            try:
                self._r = fn()
            except BaseException as exc:  # noqa: BLE001 — re-raised in result
                self._e = exc

        self._t = threading.Thread(target=run, name="bf-win-prefetch",
                                   daemon=True)
        self._t.start()

    def result(self):
        self._t.join()
        if self._e is not None:
            raise self._e
        return self._r


class _PendingDeposit:
    """Reassembly state for one in-flight deposit on one mailbox key.

    Chunks copy straight into ``target`` as they arrive (a flat uint8 view
    of the destination): the mailbox slot itself for put-mode deposits —
    the wire dtype IS the mail dtype, so a put needs no accumulation pass
    at all — or a staging buffer for accumulate-mode, folded once complete.
    This replaces the r5 join-then-frombuffer-then-cast fold (three full
    copies of every drained byte) with one copy per byte. Chunks land at
    their tag-index offset (``_place_chunk``), so a striped origin's
    out-of-order arrivals reassemble exactly; completion is by byte count."""

    __slots__ = ("mode", "has_p", "pc", "seq", "nchunks", "cap", "hdr_len",
                 "got", "seen", "staging", "target", "t0", "codec_id", "wt",
                 "expect", "shard", "discard")

    def __init__(self, mode: int, has_p: int, pc: float, seq: int,
                 nchunks: int, target: np.ndarray, staging,
                 codec_id: int = 0, wt: float = 1.0,
                 expect: int = 0, shard: int = -1,
                 discard: bool = False) -> None:
        self.mode = mode
        self.has_p = has_p
        self.pc = pc
        self.seq = seq
        self.nchunks = nchunks
        self.cap = None      # sender chunk size, learned from any non-last
        self.hdr_len = 0     # bytes carried inline by the header record
        self.got = 0
        self.seen: set = set()  # chunk indices already placed
        self.target = target    # flat uint8 view, len == expected bytes
        self.staging = staging  # acc/codec staging array (None for put)
        self.codec_id = codec_id  # wire codec (0 = legacy raw payload)
        self.wt = wt            # receiver-side edge weight (codec wire)
        self.expect = expect    # this deposit's payload byte count
        self.shard = shard      # rotation index on the wire (-1 = none)
        self.discard = discard  # shard mismatch: drop value, keep p
        self.t0 = time.monotonic()


def _win_wire_dtype(mail_dtype):
    # jnp.issubdtype: numpy's own issubdtype does not recognize the
    # ml_dtypes extension floats (bfloat16, float8_*) as np.floating
    d = jnp.dtype(mail_dtype)
    return np.dtype(d) if jnp.issubdtype(d, jnp.floating) else np.dtype(
        _win_acc_dtype(mail_dtype))


_sent_clamp_warned = False


def _max_sent_bytes() -> int:
    raw = os.environ.get("BLUEFOG_MAX_WIN_SENT_LENGTH")
    if raw is None:
        return _DEFAULT_MAX_SENT
    v = int(raw)
    if v < (1 << 16):
        # Unit change vs the reference (mpi_controller.cc:41-46): there the
        # knob counted ELEMENTS, here it counts BYTES. A sub-64 KiB value is
        # almost certainly a migrated element-count config (e.g. the
        # reference default 20000); warn once instead of silently chunking
        # at the clamp floor (docs/env_variables.md, MIGRATION.md).
        global _sent_clamp_warned
        if not _sent_clamp_warned:
            _sent_clamp_warned = True
            logger.warning(
                "BLUEFOG_MAX_WIN_SENT_LENGTH=%d is below the 64 KiB clamp "
                "floor and will be clamped. Note the unit changed vs the "
                "reference BlueFog: this knob now counts BYTES per wire "
                "chunk, not elements — a migrated element-count config "
                "should be multiplied by the element size (see "
                "MIGRATION.md).", v)
    return max(1 << 16, v)


def _pack_deposit(mode: int, has_p: int, pc: float, payload,
                  codec_id: int = 0, wt: float = 1.0,
                  shard: int = -1) -> List:
    """Split one deposit into its wire records: a header record followed by
    bounded payload chunks.

    ``payload`` may be ``bytes`` or any C-contiguous buffer (a numpy
    array): chunks are zero-copy memoryview slices, and the native
    scatter-gather write streams them straight from the source buffer — a
    100 MB deposit is chunked without a single Python-side copy. The drain
    completes a deposit by BYTE COUNT (the row size is known to both
    ends), so a header record carrying its payload inline (the compact
    single-record form) reassembles identically.

    ``codec_id``/``wt`` (compressed wire): the codec id joins the mode
    byte's high nibble and the extension header carries the edge weight
    plus the encoded byte count (the drain cannot derive it from the row
    size). ``codec_id=0`` emits exactly the legacy record layout.

    ``shard`` >= 0 (sharded rotation, ISSUE r17): sets the mode byte's
    shard flag and appends the i32 shard index so the owner's drain can
    reject a drifted rotation's coordinates (``shard=-1`` emits the
    legacy layout bit for bit)."""
    cap = _max_sent_bytes()
    if isinstance(payload, np.ndarray):
        # extension dtypes (ml_dtypes bf16/f8) lack the buffer protocol;
        # a uint8 view is always exportable and stays zero-copy
        payload = payload.reshape(-1).view(np.uint8)
    mv = memoryview(payload).cast("B")
    chunks = [mv[i:i + cap] for i in range(0, mv.nbytes, cap)]
    mode_byte = mode | (codec_id << _DEP_CODEC_SHIFT)
    if shard >= 0:
        mode_byte |= _DEP_SHARD_FLAG
    hdr = struct.pack("<BBdI", mode_byte, has_p, pc, len(chunks))
    if codec_id:
        hdr += struct.pack("<dQ", float(wt), mv.nbytes)
    if shard >= 0:
        hdr += struct.pack("<i", int(shard))
    return [hdr, *chunks]


def _blen(b) -> int:
    return len(b) if isinstance(b, (bytes, bytearray)) else \
        memoryview(b).nbytes


def _precheck_mailbox_cap(win: Window, dep_names, dep_blobs,
                          dep_edge_of) -> set:
    """Edges whose deposits would overflow the server mailbox byte cap.

    The cap check must happen at DEPOSIT granularity, not record
    granularity: a deposit is a header record plus payload chunks, and a
    server-side -2 in the middle of that sequence would leave a torn
    deposit the owner's drain can only time out on. The pre-check is
    race-free because each mailbox key has exactly ONE writer (slot (dst,
    k) maps 1:1 to a source rank owned by this controller) and the owner's
    drain only shrinks the box — a stale read is always conservative in
    the safe direction (pending can only have gone DOWN since).

    The cap value comes from the SERVING process (published at server
    startup under a well-known kv key) rather than this origin's local
    env: a cross-host ``BLUEFOG_CP_MAILBOX_MAX_MB`` mismatch would
    otherwise let the origin's pre-check pass while the server's real cap
    tears a multi-record deposit mid-sequence (ADVICE r5 low)."""
    cap = _cp.mailbox_cap_bytes()
    if cap <= 0:
        return set()
    sizes: Dict[str, int] = {}
    edge_of: Dict[str, Tuple[int, int, int]] = {}
    for nm, blob, edge in zip(dep_names, dep_blobs, dep_edge_of):
        # + _DEP_TAG: the server stores the tag prefix in the same box
        sizes[nm] = sizes.get(nm, 0) + _blen(blob) + _DEP_TAG
        edge_of[nm] = edge
    # a single deposit larger than the cap can NEVER land, drained or not
    # — that's a configuration error, not a dead-owner symptom; diagnose
    # it as such instead of the misleading "owner has not drained" path
    too_big = {nm: sizes[nm] for nm in sizes if sizes[nm] > cap}
    if too_big:
        worst = max(too_big.values())
        raise ValueError(
            f"window '{win.name}': a single deposit of {worst} bytes "
            f"exceeds the {cap}-byte mailbox cap for edges "
            f"{sorted(edge_of[nm] for nm in too_big)} — raise "
            "BLUEFOG_CP_MAILBOX_MAX_MB (it must exceed one full window "
            "row) or split the window tensor into smaller leaves")
    keys = sorted(sizes)
    pending = dict(zip(keys, _cp.client().box_bytes_many(keys)))
    return {edge_of[nm] for nm in keys
            if pending[nm] + sizes[nm] > cap}


def _assemble_global(win: Window, rows: Dict[int, np.ndarray]):
    """Build the rank-stacked global array from this controller's rows.

    Metadata-only across controllers: each controller contributes exactly its
    addressable shards (jax.make_array_from_single_device_arrays), so no
    cross-controller dispatch happens — the one-sided property survives the
    return path."""
    st = _global_state()
    sh = NamedSharding(st.mesh, P("rank"))
    shape = (st.size,) + win.row_shape
    if len(rows) == st.size:
        stacked = np.stack([rows[r] for r in range(st.size)])
        return jax.device_put(stacked, sh)
    shards = [
        jax.device_put(rows[r][None], st.devices[r]) for r in sorted(rows)
    ]
    return jax.make_array_from_single_device_arrays(shape, sh, shards)


def _get_window(name: str) -> Window:
    st = _global_state()
    st.check_initialized()
    win = st.windows.get(name)
    if win is None:
        raise ValueError(f"window '{name}' does not exist; call win_create first")
    return win


def _edge_weights(
    weights: Optional[Weights],
    neighbors: Dict[int, List[int]],
    default: float,
    what: str,
    size: int,
) -> Dict[int, Dict[int, float]]:
    """Normalize {peer: w} / nested / None into per-rank {rank: {peer: w}}."""
    if weights is None:
        return {r: {p: default for p in neighbors[r]} for r in range(size)}
    first = next(iter(weights.values()), None)
    if isinstance(first, dict):
        table = {r: dict(weights.get(r, {})) for r in range(size)}
        for r, wmap in table.items():
            extra = set(wmap) - set(neighbors[r])
            if extra:
                raise ValueError(
                    f"{what} for rank {r} references non-neighbor ranks "
                    f"{sorted(extra)}"
                )
    else:
        # flat {peer: w}: each rank uses the entries that name its neighbors;
        # a key that is nobody's neighbor is a typo, not a no-op (the
        # reference rejects non-neighbor keys, mpi_ops.py:1060-1063).
        all_neighbors = set().union(*neighbors.values()) if neighbors else set()
        extra = set(weights) - all_neighbors
        if extra:
            raise ValueError(
                f"{what} references ranks {sorted(extra)} that are not "
                f"neighbors of any rank under the current topology"
            )
        table = {
            r: {p: w for p, w in weights.items() if p in neighbors[r]}
            for r in range(size)
        }
    return table


def _edge_arrays(win: Window, table: Dict[int, Dict[int, float]]):
    """[S, n] weight + active arrays for an edge-weight table keyed by src."""
    lay = win.layout
    S = max(len(lay.shifts), 1)
    w = np.zeros((S, lay.n), np.float32)
    active = np.zeros((S, lay.n), np.float32)
    for src in range(lay.n):
        for dst, wt in table[src].items():
            si = lay.shift_index[(dst - src) % lay.n]
            w[si, dst] = wt
            active[si, dst] = 1.0
    return w, active


def _bump_host_state(win: Window, table: Dict[int, Dict[int, float]],
                     accumulate: bool) -> None:
    """Mirror version counters and associated-p scalars for touched edges."""
    st = _global_state()
    p = win.host.read_p() if st.win_ops_with_associated_p else None
    win.host.bump_versions(
        [(dst, win.layout.slot_of[dst][src])
         for src in range(win.size) for dst in table[src]])
    if st.win_ops_with_associated_p:
        for src in range(win.size):
            for dst, wt in table[src].items():
                k = win.layout.slot_of[dst][src]
                contrib = p[src] * wt
                if accumulate:
                    win.host.add_p_mail(dst, k, contrib)
                else:
                    win.host.set_p_mail(dst, k, contrib)


def _acquire(win: Window, ranks, require_mutex: bool):
    if require_mutex:
        _acquire_all(win, win.host.op_mutex_ranks(ranks))


def _acquire_all(win: Window, ranks) -> None:
    """Acquire in order, releasing everything on a mid-sequence failure
    (a dead holder's PeerLostError must not leak the earlier mutexes)."""
    acquired = []
    try:
        for r in ranks:
            win.host.mutex_acquire(r)
            acquired.append(r)
    except BaseException:
        for r in reversed(acquired):
            try:
                win.host.mutex_release(r)
            except Exception:  # noqa: BLE001 — unwind must not mask
                pass
        raise


def _release(win: Window, ranks, require_mutex: bool):
    if require_mutex:
        for r in reversed(win.host.op_mutex_ranks(ranks)):
            win.host.mutex_release(r)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    """Create a named window from a rank-stacked tensor.

    Reference: mpi_ops.py:890-915 / mpi_controller.cc:796-869. Neighbor
    buffers start as a copy of the local tensor unless ``zero_init``.
    """
    st = _global_state()
    st.check_initialized()
    _check_rank_stacked(tensor, st.size, "win_create")
    if name in st.windows:
        return False
    with timeline_context(name, "WIN_CREATE"):
        st.windows[name] = Window(name, tensor, zero_init)
    return True


def win_free(name: Optional[str] = None) -> bool:
    """Free one window, or all windows when name is None (mpi_ops.py:918-933)."""
    st = _global_state()
    st.check_initialized()
    if name is None:
        for win in st.windows.values():
            win.close()
        st.windows.clear()
        return True
    if name not in st.windows:
        return False
    st.windows[name].close()
    del st.windows[name]
    return True


# ---------------------------------------------------------------------------
# put / accumulate / get
# ---------------------------------------------------------------------------

def _send_deposits_delayed(names, blobs, tags, edge_of, delays):
    """Chaos-only deposit send (BLUEFOG_CP_FAULT ``delay_edges``):
    partition the batch by each record's injected edge delay and ship the
    groups in ascending-delay order, sleeping up to each group's delay
    first — deterministic bandwidth ASYMMETRY (slow edges land late,
    undelayed edges ship immediately), the self-tuning controller's
    slow-edge fixture. Never on the hot path: the caller only reaches
    here when the fault clause is armed."""
    groups: Dict[int, List[int]] = {}
    for i, e in enumerate(edge_of):
        groups.setdefault(int(delays.get((e[0], e[1]), 0)), []).append(i)
    replies = [0] * len(names)
    waited = 0
    for dly in sorted(groups):
        if dly > waited:
            time.sleep((dly - waited) / 1e3)
            waited = dly
        idx = groups[dly]
        sub = _cp.client().append_bytes_tagged_many(
            [names[i] for i in idx], [blobs[i] for i in idx],
            [tags[i] for i in idx])
        for i, r in zip(idx, sub):
            replies[i] = r
    return replies


def _hosted_exchange(win: Window, tensor, table, sw_list, accumulate: bool,
                     require_mutex: bool, activity: str, from_get: bool):
    """One-sided put/accumulate/get over the host tensor transport.

    Only THIS controller's owned source ranks act; contributions to remote
    destinations become server deposits (kAppendBytes) that the owning
    controller folds at its next win_update. Nothing here waits on another
    controller — the reference's passive-target property
    (mpi_controller.cc:953-1034) restated for multi-controller TPU jobs.
    """
    st = _global_state()
    acc_t = np.dtype(_win_acc_dtype(win.mail_dtype))
    owned = set(win.owned)
    if from_get:
        # a get READS the published source tensors: lock the sources
        touched = sorted({src for src in range(win.size)
                          if table[src] and set(table[src]) & owned})
    else:
        touched = sorted({dst for src in owned
                          for dst in table.get(src, {})})
    # Server locks directly (no owner filter): the origin takes the remote
    # target's mutex exactly like MPI_Win_lock on the target window. Sorted
    # order keeps concurrent origins deadlock-free.
    if require_mutex:
        _acquire_all(win, touched)
    try:
        with timeline_context(win.name, activity), _op_timer(activity), \
                win.state_mu:
            use_p = st.win_ops_with_associated_p
            if not from_get:
                # batched owned-only read: the hosted hot path never pays
                # n-scaling server round-trips for ranks it doesn't own
                p_own = win.host.read_p_owned() if use_p else None
                rows = _owned_rows(tensor, win.owned)
                # Version bumps first, ONE pipelined round-trip for every
                # touched edge (ADVICE r3: the per-edge fetch_add in the
                # loop re-introduced n-scaling latency). Bump-before-deposit
                # is also the strict-consistency ordering: a drain that
                # finds a deposit can never observe its version still at 0
                # when both sides hold the rank mutex (VERDICT r3 #7).
                edges = [(src, dst, win.layout.slot_of[dst][src])
                         for src in win.owned
                         for dst in sorted(table.get(src, {}))]
                win.host.bump_versions([(d, k) for _, d, k in edges],
                                       force=True)
                mode = _DEP_ACC if accumulate else _DEP_PUT
                wire_t = _win_wire_dtype(win.mail_dtype)
                # Remote deposits are chunked into bounded wire records and
                # shipped as ONE pipelined batch (latency no longer scales
                # with out-degree; the reference's chunked-put stream,
                # mpi_controller.cc:932-1034). Local folds stay in acc_t.
                dep_names: List[str] = []
                dep_blobs: List = []  # bytes headers + zero-copy np views
                dep_tags: List[int] = []  # (seq, index) per record
                dep_edge_of: List[Tuple[int, int, int]] = []  # per record
                dep_flows: List[Tuple[Tuple[int, int, int], int]] = []
                deposited = set()
                # sharded rotation: every deposit names the active shard
                # so a drifted owner can reject it (ISSUE r17)
                dep_shard = win.active_shard if win.shard_factor > 1 else -1
                fl = _flight.recorder()
                try:
                    for src in win.owned:
                        x = rows[src].astype(acc_t, copy=False)
                        dsts = sorted(table.get(src, {}))
                        # Compressed wire: ONE encode per source row — the
                        # payload feeds every out-edge still on the window
                        # codec (weights move receiver-side) and its
                        # decoded estimate feeds the local folds, so wire
                        # and local numerics agree. Edges carrying a
                        # per-edge override (ISSUE r16) encode separately
                        # below against their own estimator state.
                        enc = est = None
                        fold_mode = mode
                        if win.codec is not None and dsts and (
                                not win._edge_codec
                                or any((src, d) not in win._edge_codec
                                       for d in dsts)):
                            enc, est, fold_mode = win._encode_row(
                                src, x, wire_t, mode)
                        for dst in dsts:
                            wt = float(table[src][dst])
                            k = win.layout.slot_of[dst][src]
                            pc = float(p_own[src] * wt) if use_p else 0.0
                            d_enc, d_est, d_fold = enc, est, fold_mode
                            d_cid = win.codec.cid if enc is not None else 0
                            x_dst = x
                            if win._edge_codec and \
                                    (src, dst) in win._edge_codec:
                                if win._edge_codec[(src, dst)] is None:
                                    # raw override: exact wire; folds any
                                    # pre-switch EF mass (accumulate)
                                    d_enc = d_est = None
                                    d_fold = mode
                                    x_dst = win._edge_raw_base(
                                        (src, dst), x, mode)
                                else:
                                    d_enc, d_est, d_fold, d_wire = \
                                        win._encode_edge(
                                            (src, dst), x, wire_t, mode)
                                    d_cid = d_wire.cid
                            if dst in owned:
                                base_row = x_dst if d_est is None else d_est
                                # unit weights (the optimizer default)
                                # skip a full-row multiply; _fold_record
                                # never mutates its contrib
                                contrib = base_row if wt == 1.0 else \
                                    base_row * np.asarray(wt, acc_t)
                                with fl.span("win.fold", a=contrib.nbytes):
                                    win._fold_record(dst, k, d_fold,
                                                     contrib)
                                if use_p:
                                    if accumulate:
                                        win.host.add_p_mail(dst, k, pc)
                                    else:
                                        win.host.set_p_mail(dst, k, pc)
                                deposited.add((src, dst, k))
                            elif d_enc is not None:
                                # codec deposit: the encoded payload (one
                                # self-describing record) with the edge
                                # weight + byte count in the extension
                                # header; flow events below report the
                                # POST-CODEC bytes, so step attribution
                                # and the plane planner see real wire cost
                                payload = d_enc
                                recs = _pack_deposit(
                                    mode, int(use_p), pc, payload,
                                    codec_id=d_cid, wt=wt,
                                    shard=dep_shard)
                                key = win._dep_key(dst, k)
                            else:
                                # wire payload stays a live numpy buffer:
                                # _pack_deposit slices it zero-copy and the
                                # native scatter-gather write streams it
                                payload = np.ascontiguousarray(
                                    (x_dst * np.asarray(wt, acc_t)).astype(
                                        wire_t, copy=False))
                                recs = _pack_deposit(
                                    mode, int(use_p), pc, payload,
                                    shard=dep_shard)
                                key = win._dep_key(dst, k)
                            if dst not in owned:
                                win._dep_seq += 1
                                dep_names.extend([key] * len(recs))
                                dep_blobs.extend(recs)
                                dep_tags.extend(_deposit_tags(
                                    win._dep_seq, len(recs),
                                    origin=st.process_index))
                                dep_edge_of.extend(
                                    [(src, dst, k)] * len(recs))
                                # flow id == the drain-side tag sequence
                                dep_flows.append((
                                    (src, dst, k),
                                    ((st.process_index & 0x7F) << 32)
                                    | (win._dep_seq & 0xFFFFFFFF),
                                    payload.nbytes))
                        # post-send self scaling (push-sum down-weighting)
                        win._rows[src] = (
                            rows[src].astype(acc_t) * np.asarray(
                                sw_list[src], acc_t)).astype(win.dtype)
                    full: set = set()
                    if dep_names:
                        full = _precheck_mailbox_cap(
                            win, dep_names, dep_blobs, dep_edge_of)
                        if full:
                            keep = [i for i, nm in enumerate(dep_names)
                                    if dep_edge_of[i] not in full]
                            dep_names = [dep_names[i] for i in keep]
                            dep_blobs = [dep_blobs[i] for i in keep]
                            dep_tags = [dep_tags[i] for i in keep]
                            dep_edge_of = [dep_edge_of[i] for i in keep]
                    if dep_names:
                        wire_out = sum(_blen(b) for b in dep_blobs)
                        # per-step win-op wire bytes, counter-delta-
                        # verified by win_microbench's sharded probe (the
                        # shard factor's ≥0.9·S reduction claim)
                        _metrics.counter("win.deposit_bytes").inc(wire_out)
                        _dl = _native.edge_delays()
                        with fl.span("win.wire", a=wire_out):
                            if _dl:
                                replies = _send_deposits_delayed(
                                    dep_names, dep_blobs, dep_tags,
                                    dep_edge_of, _dl)
                            else:
                                replies = \
                                    _cp.client().append_bytes_tagged_many(
                                        dep_names, dep_blobs, dep_tags)
                        # backstop only: the pre-check above keeps the
                        # server cap from ever tearing a multi-record
                        # deposit; a -2 here means the client's
                        # BLUEFOG_CP_MAILBOX_MAX_MB disagrees with the
                        # server's
                        full.update(dep_edge_of[i]
                                    for i, r in enumerate(replies)
                                    if r == -2)
                        deposited.update(
                            e for i, e in enumerate(dep_edge_of)
                            if replies[i] >= 0 and e not in full)
                    if full:
                        _metrics.counter("win.deposits_rejected").inc(
                            len(full))
                        raise RuntimeError(
                            f"window '{win.name}': deposit mailbox full "
                            f"for edges (src, dst, slot) {sorted(full)} "
                            "(server byte cap, BLUEFOG_CP_MAILBOX_MAX_MB) "
                            "— the owning controller has not drained; it "
                            "may be dead (check bf.dead_controllers())")
                    # cross-process trace correlation: one flow arrow per
                    # LANDED remote deposit, id = the tag sequence the
                    # owner's drain recovers from the wire. The flight ring
                    # gets the same pairing (edge.<src>.<dst> flow starts,
                    # drain.<origin> finishes) plus per-edge byte totals —
                    # the input scripts/step_attribution.py aggregates.
                    sent = 0
                    for edge, fid, nbytes in dep_flows:
                        if edge in deposited:
                            timeline_flow_start(_FLOW_DEPOSIT, fid)
                            fl.rec(_flight.FLOW_S,
                                   fl.intern(f"edge.{edge[0]}.{edge[1]}"),
                                   nbytes, fid)
                            sent += 1
                    if sent:
                        _metrics.counter("win.deposits_sent").inc(sent)
                    with fl.span("win.publish"):
                        win._publish_selves(win.owned)
                except Exception:
                    # un-bump the edges whose deposits never landed (e.g. a
                    # full mailbox for a dead owner) so healthy neighbors'
                    # version counters don't advertise writes that will
                    # never arrive; best-effort — a broken wire fails this
                    # too, and then the job is down anyway
                    try:
                        missing = [(d, k) for s, d, k in edges
                                   if (s, d, k) not in deposited]
                        if missing:
                            win.host.bump_versions(
                                [(d, k) for d, k in missing], force=True,
                                delta=-1)
                    except Exception:  # noqa: BLE001
                        pass
                    raise
                if use_p:
                    win.host.write_p_entries({
                        src: p_own[src] * float(sw_list[src])
                        for src in win.owned})
            else:
                # pull each in-edge source's published tensor into MY
                # mailbox; a get may read a REMOTE source's p scalar.
                p_all = win.host.read_p() if use_p else None
                remote_srcs = sorted({
                    src for dst in win.owned for src in range(win.size)
                    if src not in owned and table[src].get(dst) is not None})
                pulled = []

                fl = _flight.recorder()

                def fold_src(src, val):
                    contrib_base = val.astype(acc_t, copy=False)
                    for dst in win.owned:
                        wt = table[src].get(dst)
                        if wt is None:
                            continue
                        k = win.layout.slot_of[dst][src]
                        with fl.span("win.fold", a=contrib_base.nbytes):
                            win._fold_record(
                                dst, k, _DEP_PUT,
                                contrib_base * np.asarray(wt, acc_t))
                        if use_p:
                            win.host.set_p_mail(dst, k,
                                                float(p_all[src] * wt))
                        pulled.append((dst, k))

                for src in sorted(owned):
                    if any(table[src].get(dst) is not None
                           for dst in win.owned):
                        fold_src(src, win._rows[src])
                # Remote rows: ALL sources issue in flight at once (bounded
                # by the pool width for memory), each fetched as striped
                # byte ranges over the connection pool, folding in source
                # order as they land. The r6 1-deep chain overlapped one
                # stream with one fold; with the pool the streams
                # themselves also run concurrently.
                depth = max(2, getattr(_cp.client(), "streams", 1))
                fetches: Dict[int, _Prefetch] = {}

                def launch(j):
                    fetches[j] = _Prefetch(
                        lambda s=remote_srcs[j]:
                        win._read_remote_self_view(s))

                # the pull leg is the get path's wire phase: the fold spans
                # inside carve themselves out of it for attribution
                fl.begin("win.wire")
                try:
                    for j in range(min(depth, len(remote_srcs))):
                        launch(j)
                    for j, src in enumerate(remote_srcs):
                        row, owner = fetches.pop(j).result()
                        if j + depth < len(remote_srcs):
                            launch(j + depth)
                        try:
                            fold_src(src, row)
                        finally:
                            owner.close()
                finally:
                    fl.end("win.wire")
                win.host.bump_versions(pulled)
    finally:
        if require_mutex:
            for r in reversed(touched):
                win.host.mutex_release(r)
    return _handles.allocate(f"{activity.lower()}.{win.name}",
                             np.zeros((), np.float32))


def _do_exchange(win: Window, tensor, table, sw_list, accumulate: bool,
                 require_mutex: bool, activity: str, from_get: bool = False,
                 donate_source: bool = False):
    if win.hosted:
        return _hosted_exchange(win, tensor, table, sw_list, accumulate,
                                require_mutex, activity, from_get)
    st = _global_state()
    w, active = _edge_arrays(win, table)
    if from_get:
        # A get READS the source ranks' window tensors: lock the sources
        # (the reference locks win.mutexes[src] in WinGet).
        touched = [src for src in range(win.size) if table[src]]
    else:
        # A put/accumulate WRITES the destinations' mailboxes: lock the dsts.
        touched = [dst for src in range(win.size) for dst in table[src]]
    # numpy for host-side operands: jit places them on the mesh directly; an
    # eager jnp.asarray would round-trip them through the default device.
    source = None if from_get else tensor  # get reads under lock
    sw_arr = np.asarray(sw_list, np.float32)
    # Compile-time specializations, gated on donate_source so the default
    # path keeps its ONE compiled variant (specializing on runtime weight
    # values would double every test/user compile). A donated source must
    # not be a get's x (win.self_value survives the op); with it, all-ones
    # self weights make the program's self output a pure alias of the
    # donated input — the optimizer-gossip put drops a full window of
    # alloc+copy.
    donate = donate_source and not from_get
    identity_self = donate and bool(np.all(sw_arr == 1.0))
    fn = win._exchange_fn(accumulate, donate, identity_self)
    _acquire(win, touched, require_mutex)
    try:
        with timeline_context(win.name, activity), _op_timer(activity), \
                win.state_mu:
            new_self, new_mail = fn(
                source if not from_get else win.self_value, win.mail,
                np.asarray(w), np.asarray(active), sw_arr)
            if not from_get:
                win.self_value = new_self
            win.mail = new_mail
            _bump_host_state(win, table, accumulate)
            # Barrier between the mailbox p-contributions (which read OTHER
            # ranks' pre-scale p) and the owner rescale of p below: without
            # it a fast controller could rescale before a slow one reads.
            win.host.flush()
            if st.win_ops_with_associated_p and not from_get:
                win.host.write_p(
                    win.host.read_p() * np.asarray(sw_list, np.float64))
                win.host.flush()
    finally:
        _release(win, touched, require_mutex)
    return _handles.allocate(f"{activity.lower()}.{win.name}", win.self_value)


def win_put_nonblocking(
    tensor,
    name: str,
    self_weight: Optional[Weights] = None,
    dst_weights: Optional[Weights] = None,
    require_mutex: bool = False,
    donate_source: bool = False,
) -> int:
    """Write ``tensor[src] * w`` into each destination's mailbox slot for src.

    After the sends, the locally stored window tensor becomes
    ``tensor * self_weight`` (the reference's in-place post-send scaling,
    mpi_ops.py:1036-1073).

    ``donate_source``: the caller relinquishes ``tensor`` (its buffer may
    be reused by the compiled exchange — read it again and jax raises a
    deleted-buffer error). The window optimizers pass this for their
    packed fusion buffers, which are dead after the put.
    """
    win = _get_window(name)
    st = _global_state()
    _check_rank_stacked(tensor, st.size, "win_put")
    table = _edge_weights(dst_weights, win.out_neighbors, 1.0, "dst_weights", st.size)
    sw = _per_rank(1.0 if self_weight is None else self_weight, st.size, "self_weight")
    return _do_exchange(win, tensor, table, sw, accumulate=False,
                        require_mutex=require_mutex, activity="WIN_PUT",
                        donate_source=donate_source)


def win_put(tensor, name: str, self_weight=None, dst_weights=None,
            require_mutex: bool = False, donate_source: bool = False) -> bool:
    handle = win_put_nonblocking(tensor, name, self_weight, dst_weights,
                                 require_mutex, donate_source)
    return win_wait(handle)


def win_accumulate_nonblocking(
    tensor,
    name: str,
    self_weight: Optional[Weights] = None,
    dst_weights: Optional[Weights] = None,
    require_mutex: bool = False,
    donate_source: bool = False,
) -> int:
    """Add ``tensor[src] * w`` into each destination's mailbox slot (SUM only,
    like the reference, mpi_ops.py:1168-1213). ``donate_source`` as in
    :func:`win_put_nonblocking`."""
    win = _get_window(name)
    st = _global_state()
    _check_rank_stacked(tensor, st.size, "win_accumulate")
    table = _edge_weights(dst_weights, win.out_neighbors, 1.0, "dst_weights", st.size)
    sw = _per_rank(1.0 if self_weight is None else self_weight, st.size, "self_weight")
    return _do_exchange(win, tensor, table, sw, accumulate=True,
                        require_mutex=require_mutex, activity="WIN_ACCUMULATE",
                        donate_source=donate_source)


def win_accumulate(tensor, name: str, self_weight=None, dst_weights=None,
                   require_mutex: bool = False,
                   donate_source: bool = False) -> bool:
    handle = win_accumulate_nonblocking(
        tensor, name, self_weight, dst_weights, require_mutex, donate_source
    )
    return win_wait(handle)


def win_get_nonblocking(
    name: str,
    src_weights: Optional[Weights] = None,
    require_mutex: bool = False,
) -> int:
    """Pull each source's current window tensor into the local mailbox.

    Reference: mpi_ops.py:1105-1136 / WinGet pulling from the global window
    (mpi_controller.cc:1123-1184); win_update then surfaces the values.
    """
    win = _get_window(name)
    st = _global_state()
    # src-keyed table: entry (dst pulls from src with weight w) is an edge
    # src -> dst, same wire direction as a put.
    recv_table = _edge_weights(src_weights, win.in_neighbors, 1.0,
                               "src_weights", st.size)
    table: Dict[int, Dict[int, float]] = {r: {} for r in range(st.size)}
    for dst in range(st.size):
        for src, wt in recv_table[dst].items():
            table[src][dst] = wt
    sw = [1.0] * st.size  # get leaves the stored window tensor unchanged
    return _do_exchange(win, None, table, sw, accumulate=False,
                        require_mutex=require_mutex, activity="WIN_GET",
                        from_get=True)


def win_get(name: str, src_weights=None, require_mutex: bool = False) -> bool:
    handle = win_get_nonblocking(name, src_weights, require_mutex)
    return win_wait(handle)


# ---------------------------------------------------------------------------
# update (the local combine; reference "win_sync")
# ---------------------------------------------------------------------------

def win_update(
    name: str,
    self_weight: Optional[Weights] = None,
    neighbor_weights: Optional[Weights] = None,
    reset: bool = False,
    clone: bool = False,
    require_mutex: bool = False,
):
    """Combine the window tensor with its mailbox buffers.

    result[r] = self_weight[r] * self[r] + sum_src w[r][src] * mail[(r, src)]

    Defaults mirror mpi_ops.py:958-1029: topology recv-weights when the
    topology is weighted, else the uniform 1/(indegree+1) average. ``reset``
    zeroes the buffers that were read (after the combine); ``clone`` leaves
    the stored window tensor unchanged. Versions of read buffers reset to 0.
    """
    win = _get_window(name)
    st = _global_state()
    n = st.size

    if (self_weight is None) != (neighbor_weights is None):
        raise ValueError(
            "self_weight and neighbor_weights must be presented together"
        )
    if self_weight is None:
        if st.is_topo_weighted:
            sw_list, nw_table = [], {}
            for r in range(n):
                s, w = topology_util.GetRecvWeights(st.topology, r)
                sw_list.append(s)
                nw_table[r] = w
        else:
            sw_list = []
            nw_table = {}
            for r in range(n):
                u = 1.0 / (len(win.in_neighbors[r]) + 1)
                sw_list.append(u)
                nw_table[r] = {src: u for src in win.in_neighbors[r]}
    else:
        sw_list = _per_rank(self_weight, n, "self_weight")
        nw_table = _edge_weights(
            neighbor_weights, win.in_neighbors, 1.0, "neighbor_weights", n
        )

    lay = win.layout
    nw = np.zeros((n, lay.d_max), np.float32)
    read_mask = np.zeros((n, lay.d_max), np.float32)
    for r, wmap in nw_table.items():
        for src, wt in wmap.items():
            k = lay.slot_of[r][src]
            nw[r, k] = wt
            read_mask[r, k] = 1.0

    if win.hosted:
        return _hosted_update(win, sw_list, nw_table, nw, read_mask,
                              reset, clone, require_mutex)

    with timeline_context(name, "WIN_UPDATE"), _op_timer("WIN_UPDATE"):
        _acquire(win, range(n), require_mutex)
        win.state_mu.acquire()
        try:
            fn = win._update_fn(reset)
            result, new_mail = fn(
                win.self_value, win.mail,
                np.asarray(sw_list, np.float32), np.asarray(nw),
                np.asarray(read_mask if reset else np.zeros_like(read_mask)))
            if st.win_ops_with_associated_p:
                p_mail = win.host.read_p_mail()
                new_p = np.asarray(sw_list, np.float64) * win.host.read_p() + \
                    np.sum(nw.astype(np.float64) * p_mail, axis=1)
            # versions of read buffers reset; optionally clear the buffers
            win.host.reset_versions(
                (r, lay.slot_of[r][src])
                for r, wmap in nw_table.items() for src in wmap)
            win.mail = new_mail
            if reset and st.win_ops_with_associated_p:
                win.host.write_p_mail(
                    p_mail * (1.0 - read_mask.astype(np.float64)))
            if not clone:
                win.self_value = result
                if st.win_ops_with_associated_p:
                    win.host.write_p(new_p)
            win.host.flush()
        finally:
            win.state_mu.release()
            _release(win, range(n), require_mutex)
    return result


def _hosted_update(win: Window, sw_list, nw_table, nw, read_mask,
                   reset: bool, clone: bool, require_mutex: bool,
                   return_rows: bool = False):
    """Owner-local combine for the hosted plane.

    Drains this controller's pending server deposits, folds them, then runs
    the weighted combine for OWNED ranks only — other controllers' ranks are
    their own business (that is what makes a sleeping peer harmless). The
    result is the rank-stacked global array assembled from owned shards.

    ``return_rows`` (the hybrid residual leg): skip the global assembly and
    return ``(rows, p_sums)`` — the per-owned-rank combined numpy rows and,
    when associated-p is on, the per-rank p-mailbox contraction
    ``sum(nw[r] * p_mail[r])`` (None otherwise). Used with ``clone=True``
    so the stored window rows and p scalars stay untouched.
    """
    st = _global_state()
    acc_t = np.dtype(_win_acc_dtype(win.mail_dtype))
    lay = win.layout
    with timeline_context(win.name, "WIN_UPDATE"), _op_timer("WIN_UPDATE"):
        # lock only OWNED ranks (the reference's win_update locks the local
        # window; remote ranks' updates are their owners' job)
        if require_mutex:
            _acquire_all(win, win.owned)
        win.state_mu.acquire()
        try:
            win._drain_deposits(strict=require_mutex)
            use_p = st.win_ops_with_associated_p
            if use_p:
                # batched, owned-only: no n-scaling server traffic
                p_own = win.host.read_p_owned()
                p_mail = win.host.read_p_mail_owned()
            results: Dict[int, np.ndarray] = {}
            for r in win.owned:
                # fewest full-row passes (this loop is ~10 % of a 100 MB
                # win_update): the multiply reads the stored dtype straight
                # into the acc dtype (no same-dtype .astype copy), each
                # edge folds as one multiply + one in-place add, and the
                # final cast is a no-op view when the window dtype IS the
                # acc dtype (f32/f64 windows)
                combined = np.multiply(
                    win._rows[r], np.asarray(sw_list[r], acc_t),
                    dtype=acc_t)
                for src, wt in nw_table.get(r, {}).items():
                    k = lay.slot_of[r][src]
                    np.add(combined,
                           np.multiply(win._mail_rows[r][k],
                                       np.asarray(wt, acc_t), dtype=acc_t),
                           out=combined)
                results[r] = combined.astype(win.dtype, copy=False)
                if reset:
                    keep = (1.0 - read_mask[r]).reshape(
                        (lay.d_max,) + (1,) * len(win.row_shape))
                    win._mail_rows[r] = (
                        win._mail_rows[r].astype(acc_t) * keep.astype(acc_t)
                    ).astype(win.mail_dtype)
            win.host.reset_versions(
                (r, lay.slot_of[r][src])
                for r in win.owned for src in nw_table.get(r, {}))
            if reset and use_p:
                win.host.write_p_mail_rows({
                    r: p_mail[r] * (1.0 - read_mask[r].astype(np.float64))
                    for r in win.owned})
            pub = None
            if not clone:
                for r in win.owned:
                    win._rows[r] = results[r]
                if use_p:
                    win.host.write_p_entries({
                        r: float(sw_list[r]) * p_own[r] + float(
                            np.sum(nw[r].astype(np.float64) * p_mail[r]))
                        for r in win.owned})
                # stream the publish while the result assembles below (a
                # 100 MB publish is most of a win_update's non-drain wall
                # time); joined before the locks release, so mutex-holding
                # readers still see the new value strictly after this
                # update
                pub = _Prefetch(lambda: win._publish_selves(win.owned))
            if return_rows:
                p_sums = None
                if use_p:
                    p_sums = {r: float(np.sum(nw[r].astype(np.float64)
                                              * p_mail[r]))
                              for r in win.owned}
                out = (results, p_sums)
            else:
                out = _assemble_global(win, results)
            if pub is not None:
                pub.result()
        finally:
            win.state_mu.release()
            if require_mutex:
                for r in reversed(win.owned):
                    win.host.mutex_release(r)
    return out


def win_update_then_collect(name: str, require_mutex: bool = True):
    """Sum self + all neighbor buffers, then clear them (mpi_ops.py:940-956)."""
    return win_update(
        name, self_weight=1.0,
        neighbor_weights={
            r: {src: 1.0 for src in _get_window(name).in_neighbors[r]}
            for r in range(_global_state().size)
        },
        reset=True, require_mutex=require_mutex,
    )


def win_fence(name: str) -> bool:
    """Close the window's RMA epoch: collective over all controllers.

    Reference: bf.win_fence (torch/mpi_win_ops.cc:714 DoWinFence ->
    MPI_Win_fence transport, mpi_controller.cc:917-929). On return, every
    ``win_put``/``win_accumulate``/``win_get`` issued by ANY controller
    before its fence is complete at its target — folded into the
    destination's mailbox buffers, ready for the next ``win_update``.

    Collective plane: every op is a collective program all controllers
    dispatched, so the fence reduces to the alignment barrier. Hosted
    plane: barrier (all origins' deposits reached the server) -> each owner
    drains its ranks' server mailboxes -> barrier (all owners folded).
    """
    win = _get_window(name)
    with timeline_context(name, "WIN_FENCE"), _op_timer("WIN_FENCE"):
        win.host.flush()
        if win.hosted:
            with win.state_mu:
                win._drain_deposits()
            win.host.flush()
    return True


# ---------------------------------------------------------------------------
# poll / wait / versions / mutex / associated-p
# ---------------------------------------------------------------------------

def win_poll(handle: int) -> bool:
    return _handles.poll(handle)


def win_wait(handle: int) -> bool:
    _handles.synchronize(handle)
    return True


def get_win_version(name: str, rank: Optional[int] = None) -> Dict[int, int]:
    """Versions of this rank's neighbor buffers: 0 = read since last write.

    Reference: mpi_ops.py:1257-1272. ``rank`` selects whose buffers to
    inspect (every rank is visible to the controller).
    """
    win = _get_window(name)
    r = 0 if rank is None else rank
    return {
        src: win.host.get_version(r, win.layout.slot_of[r][src])
        for src in win.in_neighbors[r]
    }


class win_mutex:
    """Acquire the window mutexes of the given ranks (default: out-neighbors).

    Context manager, matching bf.win_mutex (mpi_ops.py:1304-1336). The
    distributed fetch-and-op spin lock becomes controller-owned locks.
    """

    def __init__(self, name: str, for_self: bool = False,
                 ranks: Optional[Sequence[int]] = None, rank: int = 0) -> None:
        self._win = _get_window(name)
        if ranks is None:
            ranks = [rank] if for_self else self._win.out_neighbors[rank]
        # Explicit user request: take exactly these ranks' locks (even ones
        # another controller owns — this is how an external actor excludes
        # the collective window ops on those ranks).
        self._ranks = sorted(set(ranks))

    def __enter__(self):
        # Exception-safe multi-acquire: a PeerLostError (dead holder) on
        # the k-th rank must not leak the k-1 already-held mutexes — the
        # self-healing retry (optimizers) re-enters this context, and a
        # leaked depth count would pin those locks for the process's life.
        acquired = []
        try:
            for r in self._ranks:
                self._win.host.mutex_acquire(r)
                acquired.append(r)
        except BaseException:
            for r in reversed(acquired):
                try:
                    self._win.host.mutex_release(r)
                except Exception:  # noqa: BLE001 — unwind must not mask
                    pass
            raise
        return self

    def __exit__(self, *exc):
        for r in reversed(self._ranks):
            self._win.host.mutex_release(r)
        return False


class win_lock:
    """RMA access-epoch context manager (no-op beyond validation on TPU).

    The MPI passive epoch (MPI_Win_lock, mpi_controller.cc:1194-1237) has no
    analog: mailbox writes are always well-ordered device ops.
    """

    def __init__(self, name: str) -> None:
        _get_window(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    """The push-sum correction scalar p for ``rank`` (init 1.0)."""
    win = _get_window(name)
    return float(win.host.read_p()[0 if rank is None else rank])


def win_associated_p_all(name: str) -> np.ndarray:
    return _get_window(name).host.read_p()


def turn_on_win_ops_with_associated_p() -> None:
    _global_state().win_ops_with_associated_p = True


def turn_off_win_ops_with_associated_p() -> None:
    _global_state().win_ops_with_associated_p = False
