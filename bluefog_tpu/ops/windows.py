"""One-sided "window" ops: the asynchronous gossip subsystem.

TPU-native redesign of BlueFog's MPI-RMA windows (reference API:
torch/mpi_ops.py:890-1363; CPU transport mpi_controller.cc:796-1393; GPU
emulation nccl_controller.cc:1113-1238). True one-sided RMA does not exist on
TPU, and the reference itself proves emulation is acceptable — its NCCL path
is a two-sided protocol with a passive-recv thread. Here the emulation is a
**mailbox model**: every window keeps, per graph edge (src -> dst), a buffer
holding the last value src put/accumulated for dst — exactly the
clone-per-in-neighbor layout of WinTorchStorageManager
(mpi_win_ops.cc:83-105) — plus the rank's own window tensor. Put/get/
accumulate write mailboxes; ``win_update`` reads them and computes the
weighted combine locally, like DoWinSync's Sum/AvgWithNeighbor
(mpi_win_ops.cc:185-238).

Semantics preserved from the reference:
  * ``self_weight`` on put/accumulate rescales the locally stored window
    tensor after the send (the push-sum "self down-weighting").
  * per-edge version counters: bumped on put/get/accumulate, cleared when
    win_update reads the buffer (mpi_controller.cc:1281-1393).
  * per-rank mutexes with ``for_self`` / explicit rank lists
    (the MPI_Fetch_and_op spin-lock, mpi_controller.cc:1532-1602, becomes a
    host-side lock table owned by the controller).
  * associated-p scalars: optional parallel window carrying the push-sum
    weight, toggled globally (mpi_controller.cc:1009-1022).

On a multi-controller deployment the mailbox writes ride device-to-device
transfers scheduled by the host runtime; mutex/version state lives with the
controller, which is the natural owner the way BlueFog's coordinator owned
negotiation.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from .. import topology as topology_util
from ..runtime import handles as _handles
from ..runtime.state import _global_state
from ..runtime.timeline import timeline_context
from .neighbors import _auto_name, _check_rank_stacked, _per_rank

Weights = Union[float, Dict[int, float], Dict[int, Dict[int, float]]]


class Window:
    """Mailbox state for one named window over the current topology."""

    def __init__(self, name: str, tensor, zero_init: bool) -> None:
        st = _global_state()
        self.name = name
        self.size = st.size
        # Edges are frozen at creation time, like MPI_Win_create against the
        # GRAPH communicator; topology changes are rejected while windows
        # exist (state.set_topology).
        self.in_neighbors = {
            r: topology_util.in_neighbor_ranks(st.topology, r)
            for r in range(st.size)
        }
        self.out_neighbors = {
            r: topology_util.out_neighbor_ranks(st.topology, r)
            for r in range(st.size)
        }
        self.self_value = jnp.asarray(tensor)
        # mailbox[(dst, src)] = last value src pushed for dst
        self.mail: Dict[Tuple[int, int], jax.Array] = {}
        self.version: Dict[Tuple[int, int], int] = {}
        for dst in range(st.size):
            for src in self.in_neighbors[dst]:
                init = jnp.zeros_like(tensor[dst]) if zero_init else \
                    jnp.asarray(tensor[dst])
                self.mail[(dst, src)] = init
                self.version[(dst, src)] = 0
        # associated-p scalars (push-sum weights), one per rank + mailboxes
        self.p = np.ones(st.size, dtype=np.float64)
        self.p_mail: Dict[Tuple[int, int], float] = {
            edge: 0.0 for edge in self.mail
        }
        self.mutexes = [threading.RLock() for _ in range(st.size)]


def _get_window(name: str) -> Window:
    st = _global_state()
    st.check_initialized()
    win = st.windows.get(name)
    if win is None:
        raise ValueError(f"window '{name}' does not exist; call win_create first")
    return win


def _edge_weights(
    weights: Optional[Weights],
    neighbors: Dict[int, List[int]],
    default: float,
    what: str,
    size: int,
) -> Dict[int, Dict[int, float]]:
    """Normalize {peer: w} / nested / None into per-rank {rank: {peer: w}}."""
    if weights is None:
        return {r: {p: default for p in neighbors[r]} for r in range(size)}
    first = next(iter(weights.values()), None)
    if isinstance(first, dict):
        table = {r: dict(weights.get(r, {})) for r in range(size)}
        for r, wmap in table.items():
            extra = set(wmap) - set(neighbors[r])
            if extra:
                raise ValueError(
                    f"{what} for rank {r} references non-neighbor ranks "
                    f"{sorted(extra)}"
                )
    else:
        # flat {peer: w}: each rank uses the entries that name its neighbors;
        # a key that is nobody's neighbor is a typo, not a no-op (the
        # reference rejects non-neighbor keys, mpi_ops.py:1060-1063).
        all_neighbors = set().union(*neighbors.values()) if neighbors else set()
        extra = set(weights) - all_neighbors
        if extra:
            raise ValueError(
                f"{what} references ranks {sorted(extra)} that are not "
                f"neighbors of any rank under the current topology"
            )
        table = {
            r: {p: w for p, w in weights.items() if p in neighbors[r]}
            for r in range(size)
        }
    return table


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    """Create a named window from a rank-stacked tensor.

    Reference: mpi_ops.py:890-915 / mpi_controller.cc:796-869. Neighbor
    buffers start as a copy of the local tensor unless ``zero_init``.
    """
    st = _global_state()
    st.check_initialized()
    _check_rank_stacked(tensor, st.size, "win_create")
    if name in st.windows:
        return False
    with timeline_context(name, "WIN_CREATE"):
        st.windows[name] = Window(name, tensor, zero_init)
    return True


def win_free(name: Optional[str] = None) -> bool:
    """Free one window, or all windows when name is None (mpi_ops.py:918-933)."""
    st = _global_state()
    st.check_initialized()
    if name is None:
        st.windows.clear()
        return True
    if name not in st.windows:
        return False
    del st.windows[name]
    return True


# ---------------------------------------------------------------------------
# put / accumulate / get
# ---------------------------------------------------------------------------

def win_put_nonblocking(
    tensor,
    name: str,
    self_weight: Optional[Weights] = None,
    dst_weights: Optional[Weights] = None,
    require_mutex: bool = False,
) -> int:
    """Write ``tensor[src] * w`` into each destination's mailbox slot for src.

    After the sends, the locally stored window tensor becomes
    ``tensor * self_weight`` (the reference's in-place post-send scaling,
    mpi_ops.py:1036-1073).
    """
    win = _get_window(name)
    st = _global_state()
    _check_rank_stacked(tensor, st.size, "win_put")
    table = _edge_weights(dst_weights, win.out_neighbors, 1.0, "dst_weights", st.size)
    sw = _per_rank(1.0 if self_weight is None else self_weight, st.size, "self_weight")
    tensor = jnp.asarray(tensor)

    with timeline_context(name, "WIN_PUT"):
        for src in range(st.size):
            for dst, w in table[src].items():
                if require_mutex:
                    win.mutexes[dst].acquire()
                try:
                    win.mail[(dst, src)] = tensor[src] * w
                    win.version[(dst, src)] += 1
                    if st.win_ops_with_associated_p:
                        win.p_mail[(dst, src)] = win.p[src] * w
                finally:
                    if require_mutex:
                        win.mutexes[dst].release()
        sw_arr = jnp.asarray(sw, dtype=jnp.result_type(tensor.dtype, jnp.float32))
        win.self_value = (
            tensor * sw_arr.reshape((st.size,) + (1,) * (tensor.ndim - 1))
        ).astype(tensor.dtype)
        if st.win_ops_with_associated_p:
            win.p = win.p * np.asarray(sw)
    return _handles.allocate(f"win_put.{name}", win.self_value)


def win_put(tensor, name: str, self_weight=None, dst_weights=None,
            require_mutex: bool = False) -> bool:
    handle = win_put_nonblocking(tensor, name, self_weight, dst_weights, require_mutex)
    return win_wait(handle)


def win_accumulate_nonblocking(
    tensor,
    name: str,
    self_weight: Optional[Weights] = None,
    dst_weights: Optional[Weights] = None,
    require_mutex: bool = False,
) -> int:
    """Add ``tensor[src] * w`` into each destination's mailbox slot (SUM only,
    like the reference, mpi_ops.py:1168-1213)."""
    win = _get_window(name)
    st = _global_state()
    _check_rank_stacked(tensor, st.size, "win_accumulate")
    table = _edge_weights(dst_weights, win.out_neighbors, 1.0, "dst_weights", st.size)
    sw = _per_rank(1.0 if self_weight is None else self_weight, st.size, "self_weight")
    tensor = jnp.asarray(tensor)

    with timeline_context(name, "WIN_ACCUMULATE"):
        for src in range(st.size):
            for dst, w in table[src].items():
                if require_mutex:
                    win.mutexes[dst].acquire()
                try:
                    win.mail[(dst, src)] = win.mail[(dst, src)] + tensor[src] * w
                    win.version[(dst, src)] += 1
                    if st.win_ops_with_associated_p:
                        win.p_mail[(dst, src)] += win.p[src] * w
                finally:
                    if require_mutex:
                        win.mutexes[dst].release()
        sw_arr = jnp.asarray(sw, dtype=jnp.result_type(tensor.dtype, jnp.float32))
        win.self_value = (
            tensor * sw_arr.reshape((st.size,) + (1,) * (tensor.ndim - 1))
        ).astype(tensor.dtype)
        if st.win_ops_with_associated_p:
            win.p = win.p * np.asarray(sw)
    return _handles.allocate(f"win_accumulate.{name}", win.self_value)


def win_accumulate(tensor, name: str, self_weight=None, dst_weights=None,
                   require_mutex: bool = False) -> bool:
    handle = win_accumulate_nonblocking(
        tensor, name, self_weight, dst_weights, require_mutex
    )
    return win_wait(handle)


def win_get_nonblocking(
    name: str,
    src_weights: Optional[Weights] = None,
    require_mutex: bool = False,
) -> int:
    """Pull each source's current window tensor into the local mailbox.

    Reference: mpi_ops.py:1105-1136 / WinGet pulling from the global window
    (mpi_controller.cc:1123-1184); win_update then surfaces the values.
    """
    win = _get_window(name)
    st = _global_state()
    table = _edge_weights(src_weights, win.in_neighbors, 1.0, "src_weights", st.size)

    with timeline_context(name, "WIN_GET"):
        for dst in range(st.size):
            for src, w in table[dst].items():
                if require_mutex:
                    win.mutexes[src].acquire()
                try:
                    win.mail[(dst, src)] = win.self_value[src] * w
                    win.version[(dst, src)] += 1
                    if st.win_ops_with_associated_p:
                        win.p_mail[(dst, src)] = win.p[src] * w
                finally:
                    if require_mutex:
                        win.mutexes[src].release()
    return _handles.allocate(f"win_get.{name}", win.self_value)


def win_get(name: str, src_weights=None, require_mutex: bool = False) -> bool:
    handle = win_get_nonblocking(name, src_weights, require_mutex)
    return win_wait(handle)


# ---------------------------------------------------------------------------
# update (the local combine; reference "win_sync")
# ---------------------------------------------------------------------------

def win_update(
    name: str,
    self_weight: Optional[Weights] = None,
    neighbor_weights: Optional[Weights] = None,
    reset: bool = False,
    clone: bool = False,
    require_mutex: bool = False,
):
    """Combine the window tensor with its mailbox buffers.

    result[r] = self_weight[r] * self[r] + sum_src w[r][src] * mail[(r, src)]

    Defaults mirror mpi_ops.py:958-1029: topology recv-weights when the
    topology is weighted, else the uniform 1/(indegree+1) average. ``reset``
    zeroes the buffers that were read (after the combine); ``clone`` leaves
    the stored window tensor unchanged. Versions of read buffers reset to 0.
    """
    win = _get_window(name)
    st = _global_state()
    n = st.size

    if (self_weight is None) != (neighbor_weights is None):
        raise ValueError(
            "self_weight and neighbor_weights must be presented together"
        )
    if self_weight is None:
        if st.is_topo_weighted:
            sw_list, nw_table = [], {}
            for r in range(n):
                s, w = topology_util.GetRecvWeights(st.topology, r)
                sw_list.append(s)
                nw_table[r] = w
        else:
            sw_list = []
            nw_table = {}
            for r in range(n):
                u = 1.0 / (len(win.in_neighbors[r]) + 1)
                sw_list.append(u)
                nw_table[r] = {src: u for src in win.in_neighbors[r]}
    else:
        sw_list = _per_rank(self_weight, n, "self_weight")
        nw_table = _edge_weights(
            neighbor_weights, win.in_neighbors, 1.0, "neighbor_weights", n
        )

    with timeline_context(name, "WIN_UPDATE"):
        if require_mutex:
            for r in range(n):
                win.mutexes[r].acquire()
        try:
            slices = []
            new_p = np.array(win.p)
            for r in range(n):
                acc = sw_list[r] * win.self_value[r].astype(jnp.float32)
                for src, w in nw_table[r].items():
                    acc = acc + w * win.mail[(r, src)].astype(jnp.float32)
                slices.append(acc.astype(win.self_value.dtype))
                if st.win_ops_with_associated_p:
                    p_acc = sw_list[r] * win.p[r]
                    for src, w in nw_table[r].items():
                        p_acc += w * win.p_mail[(r, src)]
                    new_p[r] = p_acc
            result = jnp.stack(slices, axis=0)
            for r in range(n):
                for src in nw_table[r]:
                    win.version[(r, src)] = 0
                    if reset:
                        win.mail[(r, src)] = jnp.zeros_like(win.mail[(r, src)])
                        if st.win_ops_with_associated_p:
                            win.p_mail[(r, src)] = 0.0
            if not clone:
                win.self_value = result
                if st.win_ops_with_associated_p:
                    win.p = new_p
        finally:
            if require_mutex:
                for r in range(n):
                    win.mutexes[r].release()
    return result


def win_update_then_collect(name: str, require_mutex: bool = True):
    """Sum self + all neighbor buffers, then clear them (mpi_ops.py:940-956)."""
    return win_update(
        name, self_weight=1.0,
        neighbor_weights={
            r: {src: 1.0 for src in _get_window(name).in_neighbors[r]}
            for r in range(_global_state().size)
        },
        reset=True, require_mutex=require_mutex,
    )


# ---------------------------------------------------------------------------
# poll / wait / versions / mutex / associated-p
# ---------------------------------------------------------------------------

def win_poll(handle: int) -> bool:
    return _handles.poll(handle)


def win_wait(handle: int) -> bool:
    _handles.synchronize(handle)
    return True


def get_win_version(name: str, rank: Optional[int] = None) -> Dict[int, int]:
    """Versions of this rank's neighbor buffers: 0 = read since last write.

    Reference: mpi_ops.py:1257-1272. ``rank`` selects whose buffers to
    inspect (every rank is visible to the controller).
    """
    win = _get_window(name)
    r = 0 if rank is None else rank
    return {src: win.version[(r, src)] for src in win.in_neighbors[r]}


class win_mutex:
    """Acquire the window mutexes of the given ranks (default: out-neighbors).

    Context manager, matching bf.win_mutex (mpi_ops.py:1304-1336). The
    distributed fetch-and-op spin lock becomes controller-owned locks.
    """

    def __init__(self, name: str, for_self: bool = False,
                 ranks: Optional[Sequence[int]] = None, rank: int = 0) -> None:
        win = _get_window(name)
        if ranks is None:
            ranks = [rank] if for_self else win.out_neighbors[rank]
        self._locks = [win.mutexes[r] for r in sorted(set(ranks))]

    def __enter__(self):
        for lock in self._locks:
            lock.acquire()
        return self

    def __exit__(self, *exc):
        for lock in reversed(self._locks):
            lock.release()
        return False


class win_lock:
    """RMA access-epoch context manager (no-op beyond validation on TPU).

    The MPI passive epoch (MPI_Win_lock, mpi_controller.cc:1194-1237) has no
    analog: mailbox writes are always well-ordered device ops.
    """

    def __init__(self, name: str) -> None:
        _get_window(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    """The push-sum correction scalar p for ``rank`` (init 1.0)."""
    win = _get_window(name)
    if rank is None:
        return float(win.p[0])
    return float(win.p[rank])


def win_associated_p_all(name: str) -> np.ndarray:
    return np.array(_get_window(name).p)


def turn_on_win_ops_with_associated_p() -> None:
    _global_state().win_ops_with_associated_p = True


def turn_off_win_ops_with_associated_p() -> None:
    _global_state().win_ops_with_associated_p = False
