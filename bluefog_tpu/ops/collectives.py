"""Classic collectives over the rank mesh: allreduce / broadcast / allgather /
barrier / pair_gossip.

TPU-native rebuild of the reference's MPI/NCCL collective surface
(reference: torch/mpi_ops.py:60-370 API; mpi_controller.cc:101-293 transport).
All ops take rank-stacked inputs (leading dim = rank axis) and return
rank-stacked outputs, so results compose with the neighbor ops and optimizer
wrappers. Transport is XLA: psum/pmean/all_gather/ppermute over the mesh's
ICI links — there is no vendor routing (BLUEFOG_*_BY_MPI) to configure.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..runtime import handles as _handles
from ..runtime.state import _global_state
from ..runtime.timeline import timeline_context
from .neighbors import _auto_name, _check_rank_stacked
from ..utils.compat import shard_map


def _jit_smap(mesh, spec, body):
    """jit-wrapped shard_map over a variable-length tuple of leaves.

    The returned callable has stable identity, so jax's jit cache is actually
    hit on repeat calls — building ``jax.jit(shard_map(...))`` inline per op
    call would re-trace and re-lower the program every single time (~0.5 s of
    host overhead per collective on the CPU mesh). Every op below routes
    through an ``lru_cache``d builder keyed by its static parameters.
    """

    def call(leaves):
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=tuple(spec for _ in leaves),
            out_specs=tuple(spec for _ in leaves),
        )
        return mapped(*leaves)

    return jax.jit(call)


def _tree_op(fn, tensor):
    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    outs = fn(tuple(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(outs))


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce(
    tensor,
    average: bool = True,
    is_hierarchical_local: bool = False,
    name: Optional[str] = None,
):
    """Sum or average every rank's tensor; each rank gets the result.

    ``is_hierarchical_local`` restricts the reduction to this rank's machine
    group (reference: allreduce on the LOCAL comm, mpi_controller.cc:138-160).
    """
    return _handles.synchronize(
        allreduce_nonblocking(tensor, average, is_hierarchical_local, name)
    )


def allreduce_nonblocking(
    tensor,
    average: bool = True,
    is_hierarchical_local: bool = False,
    name: Optional[str] = None,
) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("allreduce", name)
    if not st.skip_negotiate:
        _check_rank_stacked(tensor, st.size, "allreduce")
    if is_hierarchical_local and st.machine_mesh is None:
        raise RuntimeError("hierarchical-local allreduce needs a homogeneous layout")

    mesh = st.machine_mesh if is_hierarchical_local else st.mesh
    with timeline_context(op_name, "ALLREDUCE"):
        out = _tree_op(
            _allreduce_fn(mesh, average, is_hierarchical_local), tensor)
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=64)
def _allreduce_fn(mesh, average: bool, hierarchical: bool):
    axis = "local" if hierarchical else "rank"
    spec = P(("machine", "local")) if hierarchical else P("rank")

    def body(*xs):
        outs = []
        for x in xs:
            acc_t = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
            red = lax.pmean(x.astype(acc_t), axis) if average else \
                lax.psum(x.astype(acc_t), axis)
            outs.append(red.astype(x.dtype))
        return tuple(outs)

    return _jit_smap(mesh, spec, body)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Every rank receives rank ``root_rank``'s slice (reference: mpi_ops.py:174-236)."""
    return _handles.synchronize(broadcast_nonblocking(tensor, root_rank, name))


def broadcast_nonblocking(tensor, root_rank: int, name: Optional[str] = None) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("broadcast", name)
    _check_rank_stacked(tensor, st.size, "broadcast")
    if not 0 <= root_rank < st.size:
        raise ValueError(f"root_rank {root_rank} out of range [0, {st.size})")

    with timeline_context(op_name, "BROADCAST"):
        out = _tree_op(_broadcast_fn(st.mesh, root_rank), tensor)
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=64)
def _broadcast_fn(mesh, root_rank: int):
    def body(*xs):
        me = lax.axis_index("rank")
        outs = []
        for x in xs:
            masked = jnp.where(me == root_rank, x, jnp.zeros_like(x))
            outs.append(lax.psum(masked, "rank").astype(x.dtype))
        return tuple(outs)

    return _jit_smap(mesh, P("rank"), body)


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather(tensor, name: Optional[str] = None):
    """Concatenate all ranks' tensors along dim 0; every rank gets the result.

    Rank-stacked in [n, b, ...] -> rank-stacked out [n, n*b, ...]. Equal
    shapes are required in the SPMD path, matching the NCCL-path restriction
    in the reference (nccl_controller.cc:389-396); use :func:`allgather_v`
    for per-rank varying first dims.
    """
    return _handles.synchronize(allgather_nonblocking(tensor, name))


def allgather_nonblocking(tensor, name: Optional[str] = None) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("allgather", name)
    _check_rank_stacked(tensor, st.size, "allgather")

    with timeline_context(op_name, "ALLGATHER"):
        out = _tree_op(_allgather_fn(st.mesh), tensor)
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=8)
def _allgather_fn(mesh):
    def body(*xs):
        outs = []
        for x in xs:
            g = lax.all_gather(x[0], "rank", axis=0, tiled=False)
            g = g.reshape((1, -1) + x.shape[2:]) if x.ndim > 1 else g.reshape(1, -1)
            outs.append(g)
        return tuple(outs)

    return _jit_smap(mesh, P("rank"), body)


def allgather_v(tensors: Sequence, name: Optional[str] = None):
    """Variable-first-dim allgather: list of per-rank arrays -> concatenation.

    The reference supports ragged gathers on its CPU/MPI path via a
    pre-allgather of first-dim sizes followed by MPI_Allgatherv
    (mpi_context.cc:443-508). The SPMD compiled path cannot trace ragged
    shapes, so the TPU-native transport is the padded analog: every rank's
    slice is zero-padded to the max first dim, the padded block rides ONE
    compiled all_gather over the mesh (real ICI traffic, not a controller
    concat), and the statically-known sizes trim the padding at the edge.
    """
    return _handles.synchronize(allgather_v_nonblocking(tensors, name))


def allgather_v_nonblocking(tensors: Sequence, name: Optional[str] = None) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("allgather_v", name)
    if len(tensors) != st.size:
        raise ValueError(f"expected {st.size} per-rank tensors, got {len(tensors)}")
    tensors = [jnp.asarray(t) for t in tensors]
    trailing = tensors[0].shape[1:]
    dtype = tensors[0].dtype
    for r, t in enumerate(tensors):
        if t.ndim < 1:
            raise ValueError(f"allgather_v: rank {r} slice must have a first dim")
        if t.shape[1:] != trailing or t.dtype != dtype:
            raise ValueError(
                f"allgather_v: rank {r} slice {t.dtype}{t.shape} does not match "
                f"rank 0's trailing shape {dtype}{(-1,) + trailing}"
            )

    sizes = tuple(int(t.shape[0]) for t in tensors)
    with timeline_context(op_name, "ALLGATHER_V"):
        if max(sizes) == 0:
            # match the compiled path's placement: replicated over the mesh,
            # not the default device (which may be a different backend)
            out = jax.device_put(
                jnp.zeros((0,) + trailing, dtype),
                jax.sharding.NamedSharding(st.mesh, P()),
            )
        else:
            out = _allgather_v_fn(st.mesh, sizes)(*tensors)
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=64)
def _allgather_v_fn(mesh, sizes: tuple):
    b_max = max(sizes)
    # static gather indices skipping each rank's padding rows
    idx = np.concatenate(
        [np.arange(r * b_max, r * b_max + s) for r, s in enumerate(sizes)]
    ).astype(np.int32)

    def body(x):
        g = lax.all_gather(x[0], "rank", axis=0, tiled=True)  # [n*b_max, ...]
        # the trim is identical on every rank, but the gather primitive defeats
        # shard_map's static replication inference, so it stays rank-stacked
        return jnp.take(g, idx, axis=0)[None]

    def call(*leaves):
        # pad + stack + row select all under one jit, so a single host
        # dispatch covers the whole op (the _jit_smap rationale applies)
        pad_trailing = [(0, 0)] * (leaves[0].ndim - 1)
        padded = jnp.stack([
            jnp.pad(t, [(0, b_max - t.shape[0])] + pad_trailing) for t in leaves
        ])
        mapped = shard_map(
            body, mesh=mesh, in_specs=P("rank"), out_specs=P("rank"))
        return mapped(padded)[0]

    return jax.jit(call)


# ---------------------------------------------------------------------------
# barrier
# ---------------------------------------------------------------------------

def barrier(name: Optional[str] = None) -> None:
    """Block until all outstanding device work completes.

    The reference implements barrier as a tiny allreduce unless negotiation
    is skipped (mpi_ops.py:872-881); on TPU a psum across the mesh plus a
    host block gives the same guarantee. Multi-controller jobs additionally
    rendezvous all controller processes through the control plane's named
    barrier (runtime/control_plane.py).
    """
    from ..runtime import control_plane as _cp

    st = _global_state()
    st.check_initialized()
    # numpy, not jnp.zeros: an eager jnp constant would materialize on the
    # DEFAULT device (possibly a different backend than the mesh) and force a
    # cross-backend transfer into the compiled program on every call.
    token = np.zeros((st.size, 1), np.float32)
    out = _barrier_fn(st.mesh)((token,))
    jax.block_until_ready(out)
    _cp.barrier(name or "bf.barrier")


@functools.lru_cache(maxsize=8)
def _barrier_fn(mesh):
    def body(x):
        return (lax.psum(x, "rank"),)

    return _jit_smap(mesh, P("rank"), body)


# ---------------------------------------------------------------------------
# pair_gossip
# ---------------------------------------------------------------------------

def pair_gossip(
    tensor,
    target_ranks: Union[Dict[int, int], Sequence[int]],
    self_weight: float = 0.5,
    pair_weight: float = 0.5,
    name: Optional[str] = None,
):
    """Exchange tensors within mutually-paired ranks and combine.

    Reference: MPI_Sendrecv-based PairGossip (mpi_controller.cc:748-774);
    each rank sends to and receives from the same target, so ``target_ranks``
    (rank -> peer) must be a symmetric pairing. Default is the plain average.
    """
    return _handles.synchronize(
        pair_gossip_nonblocking(tensor, target_ranks, self_weight, pair_weight, name)
    )


def pair_gossip_nonblocking(
    tensor,
    target_ranks: Union[Dict[int, int], Sequence[int]],
    self_weight: float = 0.5,
    pair_weight: float = 0.5,
    name: Optional[str] = None,
) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("pair_gossip", name)
    _check_rank_stacked(tensor, st.size, "pair_gossip")

    n = st.size
    if isinstance(target_ranks, dict):
        peers = [target_ranks.get(r, r) for r in range(n)]
    else:
        peers = list(target_ranks)
    if len(peers) != n:
        raise ValueError("target_ranks must give a peer for every rank")
    for r, p in enumerate(peers):
        if not 0 <= p < n:
            raise ValueError(f"peer {p} for rank {r} out of range")
        if peers[p] != r:
            raise ValueError(
                f"pair_gossip needs mutual pairs: rank {r} -> {p} but "
                f"rank {p} -> {peers[p]} (sendrecv semantics)"
            )

    with timeline_context(op_name, "PAIR_GOSSIP"):
        out = _tree_op(
            _pair_gossip_fn(st.mesh, tuple(peers),
                            float(self_weight), float(pair_weight)),
            tensor,
        )
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=128)
def _pair_gossip_fn(mesh, peers: tuple, self_weight: float, pair_weight: float):
    perm = [(p, r) for r, p in enumerate(peers)]  # rank r receives from its peer

    def body(*xs):
        outs = []
        for x in xs:
            recv = lax.ppermute(x, "rank", perm)
            outs.append((self_weight * x + pair_weight * recv).astype(x.dtype))
        return tuple(outs)

    return _jit_smap(mesh, P("rank"), body)


# ---------------------------------------------------------------------------
# in-place name-parity aliases
# ---------------------------------------------------------------------------
# The reference's trailing-underscore variants mutate the input tensor and
# return it (mpi_ops.py:150-201, 265-308). jax.Arrays are immutable: these
# aliases return the reduced value for callers to rebind, and the true
# in-place analog — reusing the input buffer — is XLA donation, which the
# fused optimizer steps already apply (optimizers.py donate_argnums).

def allreduce_(*args, **kwargs):
    """Name-parity alias of :func:`allreduce` (reference in-place variant)."""
    return allreduce(*args, **kwargs)


def allreduce_nonblocking_(*args, **kwargs) -> int:
    """Name-parity alias of :func:`allreduce_nonblocking`."""
    return allreduce_nonblocking(*args, **kwargs)


def broadcast_(*args, **kwargs):
    """Name-parity alias of :func:`broadcast` (reference in-place variant)."""
    return broadcast(*args, **kwargs)


def broadcast_nonblocking_(*args, **kwargs) -> int:
    """Name-parity alias of :func:`broadcast_nonblocking`."""
    return broadcast_nonblocking(*args, **kwargs)
