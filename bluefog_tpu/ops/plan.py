"""Combine plans: graph topologies compiled to ppermute / all-gather programs.

This module is where BlueFog's per-edge MPI/NCCL message scheduling
(reference: mpi_controller.cc:369-525, nccl_controller.cc:546-756) is replaced
by a TPU-native design. A weighted digraph over the rank axis is decomposed
into *circulant shifts*: edge set {(i, (i+s) mod n) : i} for each distinct
shift s. One shift is exactly one ``jax.lax.ppermute`` over the mesh — a
single hop on the ICI torus for ring/expo-2 style graphs — and the weighted
combine

    out[j] = W[j, j] * x[j] + sum_s W[(j-s) % n, j] * x[(j-s) % n]

is fused into the same compiled program (the reference does this combine on
the host in the binding layer after communication, torch/mpi_ops.cc:354-430;
here XLA fuses it into the collective schedule).

Two execution strategies, chosen per graph:
  * ``ppermute``: one weighted ppermute per shift. Optimal for sparse graphs
    (expo-2 has ceil(log2 n) shifts; dynamic one-peer has 1).
  * ``gather``: one tiled all-gather + an MXU matvec against the [n, n]
    weight matrix. Better for dense graphs (fully-connected, star) where the
    shift count approaches n.

Weights are *traced* (passed as device arrays), shifts are *static* (part of
the jit cache key). Dynamic topologies (per-step one-peer schedules) therefore
re-jit only per distinct shift set — the Expo-2 schedule has ceil(log2 n)
distinct sets total — and per-step weight changes are free. This resolves the
reference's "dynamic topology" re-negotiation (operations.cc:945-1000) with
zero per-step host work after warmup.
"""

from __future__ import annotations

import functools
from typing import Dict, FrozenSet, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import topology as topology_util
from ..utils.compat import shard_map


# Accumulate in f32 whenever inputs are lower precision (bf16 params on TPU):
# neighbor averaging is a convex combination and bf16 accumulation loses the
# consensus invariant tests rely on.
def _acc_dtype(dtype) -> jnp.dtype:
    return jnp.float32 if jnp.issubdtype(dtype, jnp.floating) and \
        jnp.dtype(dtype).itemsize < 4 else jnp.dtype(dtype)


class CombinePlan:
    """Host-side decomposition of a combine matrix W (edge i->j = W[i,j])."""

    __slots__ = ("n", "shifts", "rows", "W", "use_gather")

    def __init__(self, W: np.ndarray, force_gather: bool | None = None) -> None:
        W = np.asarray(W, dtype=np.float32)
        n = W.shape[0]
        assert W.shape == (n, n), "combine matrix must be square"
        self.n = n
        self.W = W
        self.shifts = tuple(topology_util.shift_support(W))
        # rows[0, j] = self weight of rank j; rows[k+1, j] = weight rank j
        # applies to the value arriving over shift k.
        rows = np.zeros((len(self.shifts) + 1, n), dtype=np.float32)
        rows[0] = np.diag(W)
        for k, s in enumerate(self.shifts):
            rows[k + 1] = [W[(j - s) % n, j] for j in range(n)]
        self.rows = rows
        if force_gather is None:
            # all-gather moves (n-1) blocks; k ppermutes move k blocks.
            self.use_gather = len(self.shifts) >= max(4, n // 2)
        else:
            self.use_gather = force_gather

    def weight_array(self) -> np.ndarray:
        return self.W if self.use_gather else self.rows


def spmd_combine(w, tree, *, axis: str, n: int, shifts: Tuple[int, ...],
                 use_gather: bool = False, stacked: bool = True):
    """Weighted neighbor combine, callable INSIDE shard_map per-rank code.

    ``w`` is the plan's traced weight array (``CombinePlan.weight_array()``):
    ``[k+1, n]`` rows for the ppermute strategy or the full ``[n, n]`` matrix
    for the gather strategy. ``shifts`` must be static. ``stacked=True`` means
    leaves carry the size-1 rank-block dim shard_map produces for
    rank-stacked arrays; ``stacked=False`` operates on bare per-rank values
    (the fused-train-step path in optimizers.py).
    """
    me = lax.axis_index(axis)

    def one(x):
        blk = x if stacked else x[None]
        acc_t = _acc_dtype(blk.dtype)
        if use_gather:
            col = jnp.take(w, me, axis=1)  # my combine column [n]
            xg = lax.all_gather(blk[0], axis, axis=0, tiled=False)  # [n, ...]
            out = jnp.tensordot(col.astype(acc_t), xg.astype(acc_t), axes=(0, 0))
            out = out.astype(x.dtype)[None]
        else:
            wm = jnp.take(w, me, axis=1)  # my weights [k+1]
            acc = wm[0].astype(acc_t) * blk.astype(acc_t)
            for k, s in enumerate(shifts):
                perm = [(i, (i + s) % n) for i in range(n)]
                moved = lax.ppermute(blk, axis, perm)
                acc = acc + wm[k + 1].astype(acc_t) * moved.astype(acc_t)
            out = acc.astype(x.dtype)
        return out if stacked else out[0]

    return jax.tree_util.tree_map(one, tree)


@functools.lru_cache(maxsize=256)
def _combine_fn(mesh: Mesh, axis: str, shifts: Tuple[int, ...], use_gather: bool,
                n_axis: int):
    """Build & cache the jitted rank-stacked combine function for one plan shape."""

    n = n_axis

    def per_rank(w, *leaves):
        return tuple(
            spmd_combine(w, x, axis=axis, n=n, shifts=shifts,
                         use_gather=use_gather)
            for x in leaves
        )

    # shard_map specs must match the number of leaves; rebuild per leaf-count
    # (traced once per shape signature under the jit below).
    def call(w, leaves: Tuple):
        mapped = shard_map(
            per_rank,
            mesh=mesh,
            in_specs=(P(),) + tuple(P(axis) for _ in leaves),
            out_specs=tuple(P(axis) for _ in leaves),
        )
        return mapped(w, *leaves)

    return jax.jit(call)


def apply_plan(plan: CombinePlan, mesh: Mesh, axis: str, tree):
    """Run the combine over a pytree of rank-stacked arrays ([n, ...] each)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    fn = _combine_fn(mesh, axis, plan.shifts, plan.use_gather, plan.n)
    # numpy, not jnp.asarray: jit places host arrays straight onto the mesh;
    # an eager conversion would hop through the default device (possibly a
    # different backend) on every call.
    outs = fn(plan.weight_array(), tuple(leaves))
    return jax.tree_util.tree_unflatten(treedef, list(outs))


# ---------------------------------------------------------------------------
# Per-edge plane planner (hybrid gossip; ISSUE r13)
# ---------------------------------------------------------------------------
#
# The hosted window plane made the plane choice per WINDOW; the planner makes
# it per EDGE. An edge is *compiled-eligible* when it can ride one fused
# shard_map/ppermute program this controller may dispatch unilaterally: both
# endpoints live (no compiled program may name a dead rank), the topology
# static (the window's edge set is frozen at creation), and the edge
# mesh-local — src and dst hosted by the SAME controller process, because a
# cross-controller collective dispatch would need the lockstep the hosted
# plane exists to avoid. Everything else — cross-controller-boundary edges,
# dead/suspect-adjacent edges, sub-floor windows — stays on the hosted
# mailbox residual with its deposit/drain semantics intact.
#
# Planner inputs: the frozen edge set, the rank→controller ownership map,
# the heartbeat dead set, the window's per-edge wire bytes (one full row per
# deposit), and — when ingested — the measured per-edge byte/wire-cost
# attribution that ``scripts/step_attribution.py --json`` emits (r12's
# step-time attribution, now a machine interface with a stable
# ``schema_version``). Partitions are cached keyed on
# (edge set, dead set, membership epoch), so elastic rejoin and self-healing
# re-plan exactly when r9's epoch fences bump and never re-derive per step.

ATTRIBUTION_SCHEMA_VERSION = 1

Edge = Tuple[int, int]


def load_attribution(doc: dict) -> Dict[Edge, dict]:
    """Per-edge cost hints from a ``step_attribution.py --json`` document.

    Returns ``{(src, dst): {"bytes": ..., "wire_sec_est": ...}}`` summed
    over ranks. Raises ValueError on a missing/unknown ``schema_version``
    — the dump is a machine interface now, and silently consuming a future
    incompatible layout would mis-plan every edge.
    """
    ver = doc.get("schema_version")
    if ver != ATTRIBUTION_SCHEMA_VERSION:
        raise ValueError(
            f"step-attribution document has schema_version={ver!r}, "
            f"expected {ATTRIBUTION_SCHEMA_VERSION} — regenerate it with "
            "this tree's scripts/step_attribution.py --json")
    hints: Dict[Edge, dict] = {}
    for rep in doc.get("ranks", {}).values():
        for label, e in rep.get("edges", {}).items():
            try:
                src, dst = (int(x) for x in label.split("->"))
            except ValueError:
                continue
            h = hints.setdefault((src, dst),
                                 {"bytes": 0.0, "wire_sec_est": 0.0})
            h["bytes"] += float(e.get("bytes", 0.0))
            h["wire_sec_est"] += float(e.get("wire_sec_est", 0.0))
    return hints


class PlanePartition(NamedTuple):
    """One planning verdict: every frozen edge lands in exactly one plane."""

    compiled: FrozenSet[Edge]
    hosted: FrozenSet[Edge]
    dead: FrozenSet[int]
    epoch: int

    @property
    def key(self):
        """Stable identity of the compiled sub-topology (jit-cache key for
        the fused program: re-jit happens only when the partition itself
        changes, never on weight changes)."""
        return tuple(sorted(self.compiled))


class PlanePlanner:
    """Per-edge plane decisions for one hosted window.

    ``policy`` mirrors ``BLUEFOG_WIN_PLANE``: only ``"auto"`` ever compiles
    an edge; ``"hosted"`` pins everything to the mailbox plane (the r6/r7
    wire, bit for bit) and ``"compiled"`` never reaches a planner at all
    (the window itself is on the collective plane). ``hosted_override`` is
    the test seam: edges forced onto the residual regardless of score.
    """

    def __init__(self, n: int, edges, owner_of: Dict[int, int],
                 row_bytes: int, min_bytes: int = 0, policy: str = "auto",
                 hosted_override=(), wire_scale: float = 1.0) -> None:
        self.n = n
        self.edges: FrozenSet[Edge] = frozenset(
            (int(s), int(d)) for s, d in edges)
        self.owner_of = dict(owner_of)
        # One full window row per deposit. Under sharded windows
        # (docs/sharded_windows.md) the window's row IS the shard row, so
        # this estimate — and every verdict derived from it — already
        # operates on shard-sized wire cost; measured attribution hints
        # are post-codec AND post-shard for the same reason (flow events
        # record the real payload).
        self.row_bytes = int(row_bytes)
        # Wire codec discount (docs/compression.md): with a codec on the
        # hosted wire, a deposit ships ~codec.nominal_ratio of the row, so
        # the static size estimate must shrink with it or the min-bytes
        # floor would mis-plan every edge. Codecs are per-EDGE since the
        # self-tuning wire (docs/self_tuning.md): ``edge_scale`` carries
        # each overridden edge's own nominal ratio and the scalar stays
        # the fallback for every other edge. Measured attribution hints
        # (ingest_attribution) already carry POST-codec bytes — the
        # edge.<src>.<dst> flow events record the encoded payload size —
        # so they are never rescaled here.
        self.wire_scale = float(wire_scale)
        self.edge_scale: Dict[Edge, float] = {}
        self.min_bytes = int(min_bytes)
        self.policy = policy
        self.hosted_override = frozenset(hosted_override)
        self.hints: Optional[Dict[Edge, dict]] = None
        # Online per-edge measured bytes (the r19 tuner's live feed):
        # highest-precedence cost source, replacing the offline --json
        # attribution dump with the streaming telemetry plane's numbers.
        self.live: Dict[Edge, float] = {}
        self.rebuilds = 0  # cache misses — asserted by the re-plan tests
        self._cache: Dict[Tuple, PlanePartition] = {}

    def ingest_attribution(self, doc: dict) -> int:
        """Feed a real ``step_attribution.py --json`` dump; its measured
        per-edge bytes replace the static row-size estimate in
        :meth:`edge_cost`. Returns the number of edges with hints and
        drops the partition cache (new inputs → new plans)."""
        self.hints = load_attribution(doc)
        self._cache.clear()
        return len(self.hints)

    def _floor_verdicts(self) -> Tuple[bool, ...]:
        """Each edge's size-floor verdict, in sorted edge order — the only
        part of eligibility that cost inputs can move."""
        return tuple(self.edge_cost(e) >= self.min_bytes
                     for e in sorted(self.edges))

    def ingest_live(self, edge_bytes: Dict[Edge, float]) -> bool:
        """Online measured per-edge wire bytes (per gossip step), fed by
        the runtime tuner from the streaming telemetry plane's per-edge
        estimators. Replaces both the static estimate and any offline
        attribution hints for the named edges.

        Re-plans ONLY on decision change: the partition cache is dropped
        when some edge's size-floor verdict actually flips, so a stream
        of measurements that all land on the same side of the floor
        costs a dict update and nothing else. Returns True when the next
        :meth:`partition` call will re-derive."""
        before = self._floor_verdicts()
        for edge, nbytes in edge_bytes.items():
            self.live[(int(edge[0]), int(edge[1]))] = float(nbytes)
        if self._floor_verdicts() == before:
            return False
        self._cache.clear()
        return True

    def set_edge_scale(self, edge: Edge, scale: float) -> bool:
        """Pin one edge's wire-scale (its codec's nominal ratio after a
        per-edge codec switch). Same decision-change gating as
        :meth:`ingest_live`; returns True when the partition will
        re-derive."""
        before = self._floor_verdicts()
        self.edge_scale[(int(edge[0]), int(edge[1]))] = float(scale)
        if self._floor_verdicts() == before:
            return False
        self._cache.clear()
        return True

    def edge_cost(self, edge: Edge) -> float:
        """Wire bytes one gossip step moves over this edge if it stays
        hosted. Precedence: live measured bytes (tuner feed, post-codec)
        > offline attribution hints (post-codec) > the window row size
        scaled by the edge's codec nominal ratio (``edge_scale``, falling
        back to the window-wide scalar)."""
        if edge in self.live:
            return self.live[edge]
        if self.hints is not None and edge in self.hints:
            return float(self.hints[edge]["bytes"])
        return float(self.row_bytes) * self.edge_scale.get(
            edge, self.wire_scale)

    def _eligible(self, edge: Edge, dead: FrozenSet[int]) -> bool:
        src, dst = edge
        if src in dead or dst in dead:
            return False  # dead/suspect-adjacent → hosted residual
        if edge in self.hosted_override:
            return False
        owner_s = self.owner_of.get(src)
        owner_d = self.owner_of.get(dst)
        if owner_s is None or owner_s != owner_d:
            return False  # cross-controller boundary → hosted residual
        if self.edge_cost(edge) < self.min_bytes:
            return False  # below the floor, hosted latency beats a re-jit
        return True

    def partition(self, dead=frozenset(), epoch: int = 0) -> PlanePartition:
        """The cached per-edge plane split for (dead set, membership epoch).

        The epoch rides the key even though the verdict depends only on
        the dead set: an epoch bump (join/leave/re-admission, r9 fences)
        is the externally visible "membership changed" signal, and keying
        on it guarantees a re-plan exactly then — the property the
        epoch-bump invalidation test pins."""
        dead = frozenset(dead)
        key = (dead, int(epoch))
        part = self._cache.get(key)
        if part is not None:
            return part
        self.rebuilds += 1
        if self.policy != "auto":
            compiled: FrozenSet[Edge] = frozenset()
        else:
            compiled = frozenset(
                e for e in self.edges if self._eligible(e, dead))
        part = PlanePartition(compiled, self.edges - compiled, dead,
                              int(epoch))
        if len(self._cache) > 32:  # dead sets churn at most with membership
            self._cache.clear()
        self._cache[key] = part
        return part


def rank_sharding(mesh: Mesh, axis: str = "rank") -> NamedSharding:
    """Sharding that lays a rank-stacked array out one-slice-per-device."""
    return NamedSharding(mesh, P(axis))


def shard_rank_stacked(mesh: Mesh, tree, axis: str = "rank"):
    """Place a rank-stacked pytree so slice r lives on device r."""
    sh = rank_sharding(mesh, axis)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
