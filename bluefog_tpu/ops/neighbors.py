"""Neighbor collectives: weighted averaging over the virtual topology.

TPU-native rebuild of BlueFog's neighbor ops (reference: torch/mpi_ops.py
:423-741 for the API contract, mpi_controller.cc:369-525 for the transport).
All ops act on *rank-stacked* arrays/pytrees: leading dimension = rank axis of
the device mesh, slice ``x[r]`` is rank r's tensor and lives on device r.
One call computes every rank's result inside a single SPMD program.

Weight semantics follow the reference exactly:
  * static unweighted topology -> uniform 1/(indegree+1) averaging
  * static weighted topology   -> the graph's recv weights (GetRecvWeights)
  * explicit self/neighbor weights -> user-specified convex (or not) combine
  * dynamic ``send_neighbors``  -> per-step edge sets; receiving weights must
    be supplied, and ``enable_topo_check`` validates the send/recv pattern
    (the analog of CheckNeighborSendRecvPattern, mpi_controller.cc:296-345).
"""

from __future__ import annotations

import functools
import hashlib
import os
import time
from typing import Dict, List, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import topology as topology_util
from ..runtime import handles as _handles
from ..runtime.state import _global_state
from ..runtime.timeline import timeline_context
from .plan import CombinePlan, apply_plan
from ..utils.compat import shard_map

Weights = Union[float, Dict[int, float]]
NestedWeights = Union[Dict[int, float], Dict[int, Dict[int, float]]]

_op_counter = [0]


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name is not None:
        return name
    _op_counter[0] += 1
    return f"{prefix}.noname.{_op_counter[0]}"


def _check_rank_stacked(tree, n: int, op: str) -> None:
    for leaf in jax.tree_util.tree_leaves(tree):
        if leaf.ndim < 1 or leaf.shape[0] != n:
            raise ValueError(
                f"{op}: expected rank-stacked input with leading dim {n} "
                f"(one slice per rank), got shape {leaf.shape}"
            )


def _per_rank(value, size: int, what: str) -> List:
    """Broadcast a scalar-or-dict per-rank argument to a dense list."""
    if isinstance(value, dict):
        missing = set(range(size)) - set(value)
        if missing:
            raise ValueError(f"{what} missing entries for ranks {sorted(missing)}")
        return [value[r] for r in range(size)]
    return [value] * size


def _static_weight_matrix(self_weight, neighbor_weights) -> np.ndarray:
    """W for the current static topology, honoring user weight overrides."""
    st = _global_state()
    n = st.size
    W = np.zeros((n, n), dtype=np.float64)
    if self_weight is None and neighbor_weights is None:
        if st.is_topo_weighted:
            for r in range(n):
                sw, nw = topology_util.GetRecvWeights(st.topology, r)
                W[r, r] = sw
                for src, w in nw.items():
                    W[src, r] = w
        else:
            for r in range(n):
                nbrs = topology_util.in_neighbor_ranks(st.topology, r)
                u = 1.0 / (len(nbrs) + 1)
                W[r, r] = u
                for src in nbrs:
                    W[src, r] = u
    else:
        if (self_weight is None) != (neighbor_weights is None):
            raise ValueError(
                "self_weight and neighbor_weights must be given together"
            )
        sw_list = _per_rank(self_weight, n, "self_weight")
        in_nbrs = {
            r: set(topology_util.in_neighbor_ranks(st.topology, r))
            for r in range(n)
        }
        first = next(iter(neighbor_weights.values()), None)
        if isinstance(first, dict):
            nw_per_rank = _per_rank(neighbor_weights, n, "neighbor_weights")
            for r in range(n):
                extra = set(nw_per_rank[r]) - in_nbrs[r]
                if extra:
                    raise ValueError(
                        f"neighbor_weights for rank {r} contain "
                        f"non-in-neighbor ranks {sorted(extra)}"
                    )
        else:
            # flat {src: w}: each rank applies the entries naming its actual
            # in-neighbors (the per-process dict of the reference,
            # mpi_ops.py:440-460, assembled for all ranks at once).
            union = set().union(*in_nbrs.values()) if in_nbrs else set()
            extra = set(neighbor_weights) - union
            if extra:
                raise ValueError(
                    f"neighbor_weights reference ranks {sorted(extra)} that "
                    f"are not in-neighbors of any rank"
                )
            nw_per_rank = [
                {s: w for s, w in neighbor_weights.items() if s in in_nbrs[r]}
                for r in range(n)
            ]
        for r in range(n):
            W[r, r] = sw_list[r]
            for src, w in nw_per_rank[r].items():
                W[src, r] = w
    return W


def _dynamic_weight_matrix(
    size: int,
    send_neighbors,
    self_weight,
    neighbor_weights,
    enable_topo_check: bool,
) -> np.ndarray:
    """W for one dynamic step from per-rank send lists + recv weights."""
    if isinstance(send_neighbors, dict):
        send_map = {r: list(send_neighbors.get(r, [])) for r in range(size)}
    else:
        if len(send_neighbors) != size:
            raise ValueError(
                "send_neighbors must map every rank to its destination list"
            )
        send_map = {r: list(send_neighbors[r]) for r in range(size)}
    for r, dsts in send_map.items():
        if len(set(dsts)) != len(dsts):
            raise ValueError(f"send_neighbors[{r}] has duplicate ranks")
    if self_weight is None or neighbor_weights is None:
        raise ValueError(
            "self_weight and neighbor_weights are required with send_neighbors"
        )

    recv_from: Dict[int, List[int]] = {r: [] for r in range(size)}
    for src, dsts in send_map.items():
        for dst in dsts:
            recv_from[dst].append(src)

    sw_list = _per_rank(self_weight, size, "self_weight")
    first = next(iter(neighbor_weights.values()), None)
    if isinstance(first, dict):
        nw_per_rank = {r: dict(neighbor_weights.get(r, {})) for r in range(size)}
    else:
        # flat {src: w}: every rank uses the same recv-weight table, filtered
        # to the sources actually sending to it this step.
        nw_per_rank = {
            r: {s: neighbor_weights[s] for s in recv_from[r] if s in neighbor_weights}
            for r in range(size)
        }

    if enable_topo_check:
        for dst in range(size):
            expected = set(recv_from[dst])
            declared = set(nw_per_rank[dst])
            if expected != declared:
                raise RuntimeError(
                    f"dynamic topology mismatch at rank {dst}: senders "
                    f"{sorted(expected)} vs declared neighbor_weights "
                    f"{sorted(declared)} (set enable_topo_check=False to skip)"
                )

    W = np.zeros((size, size), dtype=np.float64)
    for dst in range(size):
        W[dst, dst] = sw_list[dst]
        for src, w in nw_per_rank[dst].items():
            W[src, dst] = w
    if enable_topo_check:
        cross_controller_topo_check(W)
    return W


def _w_hash(W: np.ndarray) -> str:
    return hashlib.sha1(np.ascontiguousarray(W).tobytes()).hexdigest()[:24]


def cross_controller_topo_check(W: Optional[np.ndarray],
                                w_hash: Optional[str] = None) -> None:
    """Verify every controller computed the SAME dynamic combine matrix.

    The reference's ``enable_topo_check`` allgathers the send/recv boolean
    matrix across processes each dynamic step
    (mpi_controller.cc:296-345). Multi-controller analog: each controller
    publishes a hash of its step's W matrix under a per-hash rendezvous
    counter on the control plane and waits until all ``world`` controllers
    have checked in. Agreement = everyone increments the SAME hash key, so
    equality needs no second exchange. Divergence = some controller waits on
    a hash key its peers never touch, and the bounded wait raises instead of
    letting different edge sets silently corrupt the ppermutes.

    Each distinct W pays this once per process: agreed hashes are cached on
    the runtime state (reset at init/set_topology), so warm steps of a
    cyclic schedule cost nothing. The cache alone has a blind spot — two
    controllers at DIFFERENT positions of the same cyclic schedule hold
    matrices that were each individually agreed in the past and would both
    cache-hit forever (VERDICT r3 weak #4). Closed by a periodic re-arm:
    every ``BLUEFOG_TOPO_CHECK_REARM`` (default 50, 0 disables) topo-checked
    calls, the rendezvous runs again. Re-arm rounds pair up by a
    server-side ticket counter (``round = fetch_add // world``), NOT the
    local call count, so agreement never assumes identical call counts
    across controllers; check-ins reuse ONE fixed key per controller with
    (round, hash-prefix) packed into the value, so re-arms add zero keys
    over the job's lifetime. In-step controllers meet at the same round
    with the same hash and pay one pipelined round-trip per K steps;
    de-synced ones collide at the same round with different hashes and
    raise — the reference's per-step CheckNeighborSendRecvPattern
    guarantee at 1/K amortized cost. ``BLUEFOG_TOPO_CHECK_REARM`` must be
    set identically on every controller (a mismatch skews the ticket
    counter and surfaces as a rendezvous timeout, not silent corruption).
    """
    from ..runtime import control_plane as _cp

    if not (_cp.active() and _cp.world() > 1):
        return
    st = _global_state()
    h = w_hash if w_hash is not None else _w_hash(W)
    st._topo_check_calls += 1
    rearm_every = int(os.environ.get("BLUEFOG_TOPO_CHECK_REARM", "50"))
    rearm = rearm_every > 0 and st._topo_check_calls % rearm_every == 0
    timeout = float(os.environ.get("BLUEFOG_TOPO_CHECK_TIMEOUT", "30"))
    if h not in st._topo_check_agreed:
        cl = _cp.client()
        world = _cp.world()
        # First-time agreement on a NEW matrix: idempotent per-controller
        # check-in (one key per controller, not a shared counter), so a
        # controller retrying after a failed rendezvous cannot inflate the
        # count into false agreement. One key set per DISTINCT matrix —
        # bounded by the schedule's period, not the step count. Key
        # lifetime == the control-plane server == the job (the launcher's
        # process 0 serves in-process); an externally shared long-lived
        # server must be restarted between jobs.
        tag = f"tc.{h}"
        cl.put(f"{tag}.{st.process_index}", 1)
        keys = [f"{tag}.{p}" for p in range(world)]
        deadline = time.monotonic() + timeout
        while True:
            agreed = sum(1 for v in cl.get_many(keys) if v)
            if agreed >= world:
                st._topo_check_agreed.add(h)
                break
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"cross-controller topology check failed: controller "
                    f"{st.process_index} computed combine-matrix hash {h} "
                    f"but only {agreed}/{world} controllers agreed within "
                    f"{timeout:.0f}s — controllers are dispatching "
                    "DIFFERENT dynamic edge sets (check the per-step "
                    "send_neighbors/neighbor_weights derivation, or set "
                    "enable_topo_check=False to skip)")
            time.sleep(0.02)
    if rearm:
        _rearm_rendezvous(h, timeout)


_H40_MASK = (1 << 40) - 1


def _rearm_rendezvous(h: str, timeout: float) -> None:
    """Periodic re-agreement that catches phase-shifted cyclic schedules.

    Every controller posts (round+1, 40-bit hash prefix) packed into its own
    fixed key ``tc.rearm.<rank>`` (the +1 keeps 0 = "never checked in") and
    waits until every peer's value is either the same round with the SAME
    hash, or a LATER round (a peer can only advance past round r after
    everyone — including us — checked in at r with a matching hash). Same
    round + different hash = controllers dispatching different steps of the
    schedule: raise. The round number comes from a shared fetch_add ticket
    (``ticket // world``), so pairing is by global arrival order and never
    assumes controllers counted the same number of local topo-check calls.
    """
    from ..runtime import control_plane as _cp

    st = _global_state()
    cl = _cp.client()
    world = _cp.world()
    rnd = cl.fetch_add("tc.rearm.tickets", 1) // world
    h40 = int(h[:10], 16) & _H40_MASK
    cl.put(f"tc.rearm.{st.process_index}", ((rnd + 1) << 40) | h40)
    keys = [f"tc.rearm.{p}" for p in range(world)]
    deadline = time.monotonic() + timeout
    while True:
        agreed = 0
        for p, v in zip(range(world), cl.get_many(keys)):
            peer_rnd, peer_h40 = (v >> 40) - 1, v & _H40_MASK
            if v and peer_rnd == rnd and peer_h40 != h40:
                raise RuntimeError(
                    f"cross-controller topology re-check failed: at re-arm "
                    f"round {rnd} controller {st.process_index} holds "
                    f"combine-matrix hash {h} but controller {p} checked in "
                    "a DIFFERENT matrix — controllers are de-synced inside "
                    "the dynamic schedule (phase-shifted cyclic edge sets), "
                    "or BLUEFOG_TOPO_CHECK_REARM differs across controllers")
            if v and peer_rnd >= rnd:
                agreed += 1
        if agreed >= world:
            return
        if time.monotonic() >= deadline:
            raise RuntimeError(
                f"cross-controller topology re-check failed: controller "
                f"{st.process_index} waited {timeout:.0f}s at re-arm round "
                f"{rnd} (hash {h}) with only {agreed}/{world} controllers "
                "checked in — a peer is stalled, crashed, or running with a "
                "different BLUEFOG_TOPO_CHECK_REARM cadence")
        time.sleep(0.02)


# ---------------------------------------------------------------------------
# neighbor_allreduce
# ---------------------------------------------------------------------------

def neighbor_allreduce(
    tensor,
    self_weight: Optional[Weights] = None,
    neighbor_weights: Optional[NestedWeights] = None,
    send_neighbors=None,
    enable_topo_check: bool = True,
    name: Optional[str] = None,
):
    """Weighted average of each rank's tensor with its in-neighbors.

    Blocking variant (reference: mpi_ops.py:481-528). ``tensor`` is a
    rank-stacked array or pytree; returns the same structure where slice j is

        W[j,j] * x[j] + sum_{i in N_in(j)} W[i,j] * x[i].
    """
    handle = neighbor_allreduce_nonblocking(
        tensor, self_weight, neighbor_weights, send_neighbors,
        enable_topo_check, name,
    )
    return _handles.synchronize(handle)


def neighbor_allreduce_nonblocking(
    tensor,
    self_weight: Optional[Weights] = None,
    neighbor_weights: Optional[NestedWeights] = None,
    send_neighbors=None,
    enable_topo_check: bool = True,
    name: Optional[str] = None,
) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("neighbor_allreduce", name)
    if not st.skip_negotiate:
        _check_rank_stacked(tensor, st.size, "neighbor_allreduce")

    if send_neighbors is None:
        key = ("static_nar", id(st.topology), st.is_topo_weighted,
               self_weight is None,
               _freeze(self_weight), _freeze(neighbor_weights))
        plan = st._plan_cache.get(key)
        if plan is None:
            with timeline_context(op_name, "PLAN_BUILD"):
                W = _static_weight_matrix(self_weight, neighbor_weights)
                plan = CombinePlan(W)
            st._plan_cache[key] = plan
    else:
        # Per-(edge set, weights) plan cache: a cyclic dynamic schedule
        # (e.g. one-peer Expo-2) revisits the same arguments every cycle,
        # and rebuilding the O(n^2) numpy W + CombinePlan + hash per step
        # was the dominant host cost at large n (VERDICT r3 weak #6 / #9).
        # Freezing the args is O(edges); everything heavier runs once per
        # distinct step of the schedule.
        key = ("dyn_nar", _freeze(send_neighbors), _freeze(self_weight),
               _freeze(neighbor_weights))
        cached = st._plan_cache.get(key)
        if cached is None:
            with timeline_context(op_name, "PLAN_BUILD"):
                W = _dynamic_weight_matrix(
                    st.size, send_neighbors, self_weight, neighbor_weights,
                    enable_topo_check,
                )
                plan = CombinePlan(W)
            if len(st._plan_cache) > 4096:  # unbounded schedules: keep sane
                # Evict only the dynamic-schedule entries: static plans (and
                # their jit-traced CombinePlans) are few, hot, and expensive
                # to rebuild — churning them because a dynamic schedule
                # overflowed the cache re-pays unrelated compilations.
                for k in [k for k in st._plan_cache if k[0] == "dyn_nar"]:
                    del st._plan_cache[k]
            st._plan_cache[key] = (plan, _w_hash(W))
        else:
            plan, h = cached
            if enable_topo_check:
                # cache-hit steps still count toward (and trigger) the
                # periodic cross-controller re-arm — see the blind-spot
                # note in cross_controller_topo_check
                cross_controller_topo_check(None, w_hash=h)

    with timeline_context(op_name, "NEIGHBOR_ALLREDUCE"):
        out = apply_plan(plan, st.mesh, "rank", tensor)
    return _handles.allocate(op_name, out)


def _freeze(obj):
    """Hashable snapshot of weight arguments for the plan cache."""
    if obj is None:
        return None
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, tuple)):
        return tuple(_freeze(v) for v in obj)
    return obj


# ---------------------------------------------------------------------------
# hierarchical_neighbor_allreduce
# ---------------------------------------------------------------------------

def hierarchical_neighbor_allreduce(
    tensor,
    self_weight: Optional[Weights] = None,
    neighbor_machine_weights: Optional[NestedWeights] = None,
    send_neighbor_machines=None,
    enable_topo_check: bool = False,
    name: Optional[str] = None,
):
    """Machine-level neighbor averaging: intra-machine allreduce then
    machine-graph weighted combine (reference: mpi_ops.py:587-741,
    mpi_controller.cc:455-515).

    The reference's 3-phase scheme (local allreduce, local-rank-0 exchange,
    local bcast) collapses on TPU: ``pmean`` over the ``local`` mesh axis then
    weighted ``ppermute`` over the ``machine`` axis — every device participates
    in the machine exchange over its own ICI links, and the "bcast" phase is
    free because each machine's devices compute identical combines.
    """
    handle = hierarchical_neighbor_allreduce_nonblocking(
        tensor, self_weight, neighbor_machine_weights, send_neighbor_machines,
        enable_topo_check, name,
    )
    return _handles.synchronize(handle)


def hierarchical_neighbor_allreduce_nonblocking(
    tensor,
    self_weight: Optional[Weights] = None,
    neighbor_machine_weights: Optional[NestedWeights] = None,
    send_neighbor_machines=None,
    enable_topo_check: bool = False,
    name: Optional[str] = None,
) -> int:
    st = _global_state()
    st.check_initialized()
    if st.machine_mesh is None:
        raise RuntimeError(
            "hierarchical ops need a homogeneous machine layout "
            "(reference requires is_homogeneous too, mpi_ops.py:693-741)"
        )
    op_name = _auto_name("hierarchical_neighbor_allreduce", name)
    if not st.skip_negotiate:
        _check_rank_stacked(tensor, st.size, "hierarchical_neighbor_allreduce")

    m = st.size // st.local_size
    if send_neighbor_machines is None and neighbor_machine_weights is None:
        # Default: machine-level Expo-2 graph, uniform weights.
        mtopo = topology_util.ExponentialTwoGraph(m)
        Wm = np.zeros((m, m))
        for r in range(m):
            nbrs = topology_util.in_neighbor_ranks(mtopo, r)
            u = 1.0 / (len(nbrs) + 1)
            Wm[r, r] = u
            for src in nbrs:
                Wm[src, r] = u
    else:
        if neighbor_machine_weights is None or self_weight is None:
            raise ValueError(
                "self_weight and neighbor_machine_weights must be given together"
            )
        if send_neighbor_machines is None:
            raise ValueError("send_neighbor_machines is required")
        Wm = _dynamic_weight_matrix(
            m, send_neighbor_machines, self_weight, neighbor_machine_weights,
            enable_topo_check,
        )

    plan = CombinePlan(Wm)

    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    fn = _hierarchical_fn(st.machine_mesh, plan.shifts, plan.n)
    with timeline_context(op_name, "HIERARCHICAL_NEIGHBOR_ALLREDUCE"):
        outs = fn(plan.rows, tuple(leaves))
    out = jax.tree_util.tree_unflatten(treedef, list(outs))
    return _handles.allocate(op_name, out)


@functools.lru_cache(maxsize=128)
def _hierarchical_fn(mesh, shifts: tuple, n_machines: int):
    """Cached local-pmean + machine-ppermute program (stable jit identity)."""

    def per_rank(w, *xs):
        mid = lax.axis_index("machine")
        wm = jnp.take(w, mid, axis=1)
        outs = []
        for x in xs:
            acc_t = jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
            xl = lax.pmean(x.astype(acc_t), "local")
            acc = wm[0].astype(acc_t) * xl
            for k, s in enumerate(shifts):
                perm = [(i, (i + s) % n_machines) for i in range(n_machines)]
                acc = acc + wm[k + 1].astype(acc_t) * lax.ppermute(xl, "machine", perm)
            outs.append(acc.astype(x.dtype))
        return tuple(outs)

    def call(w, leaves):
        mapped = shard_map(
            per_rank,
            mesh=mesh,
            in_specs=(P(),) + tuple(P(("machine", "local")) for _ in leaves),
            out_specs=tuple(P(("machine", "local")) for _ in leaves),
        )
        return mapped(w, *leaves)

    return jax.jit(call)


# ---------------------------------------------------------------------------
# neighbor_allgather
# ---------------------------------------------------------------------------

def neighbor_allgather(tensor, name: Optional[str] = None):
    """Concatenate each rank's in-neighbor tensors (self excluded).

    Reference: mpi_ops.py:378-415; neighbor order is sorted in-neighbor rank
    (the MPI_Dist_graph ordering contract, torch/mpi_ops.cc:374-380).

    For regular graphs returns a rank-stacked array [n, indeg*b, ...]; for
    irregular graphs (star) returns a list of per-rank arrays, since indegree
    — and hence the output shape — varies per rank.
    """
    handle = neighbor_allgather_nonblocking(tensor, name)
    return _handles.synchronize(handle)


@functools.lru_cache(maxsize=128)
def _gather_exchange_fn(mesh, shifts: tuple, n: int, d_max: int):
    """Compiled in-neighbor exchange: one ppermute per shift, slot scatter.

    Each rank receives one value per incoming circulant shift and writes it
    into slot j of a [d_max, ...] buffer, where j is the source's position in
    the rank's *sorted* in-neighbor list — the MPI_Dist_graph ordering the
    reference guarantees (mpi_controller.cc:251-293) — so the later reshape
    is exactly the sorted-neighbor concatenation. Slots with no neighbor
    (irregular graphs, padded to d_max) stay zero and are sliced away by the
    caller. The slot table is traced, so per-rank irregularity costs nothing
    at compile time; shifts are static like every CombinePlan.
    """

    def per_rank(slot, *xs):
        me = lax.axis_index("rank")
        outs = []
        for x in xs:
            xb = x[0]
            out = jnp.zeros((d_max,) + xb.shape, xb.dtype)
            for si, s in enumerate(shifts):
                perm = [(i, (i + s) % n) for i in range(n)]
                moved = lax.ppermute(xb, "rank", perm)  # from (me - s) % n
                k = slot[si, me]
                kk = jnp.maximum(k, 0)
                cur = lax.dynamic_index_in_dim(out, kk, 0, keepdims=False)
                val = jnp.where(k >= 0, moved, cur)
                out = lax.dynamic_update_index_in_dim(out, val, kk, axis=0)
            outs.append(out[None])
        return tuple(outs)

    def call(slot, leaves):
        mapped = shard_map(
            per_rank,
            mesh=mesh,
            in_specs=(P(),) + tuple(P("rank") for _ in leaves),
            out_specs=tuple(P("rank") for _ in leaves),
        )
        return mapped(slot, *leaves)

    return jax.jit(call)


def neighbor_allgather_nonblocking(tensor, name: Optional[str] = None) -> int:
    st = _global_state()
    st.check_initialized()
    op_name = _auto_name("neighbor_allgather", name)
    _check_rank_stacked(tensor, st.size, "neighbor_allgather")
    for leaf in jax.tree_util.tree_leaves(tensor):
        if leaf.ndim < 2:
            raise ValueError(
                "neighbor_allgather concatenates per-rank tensors along their "
                "first dimension, so rank-stacked input needs >= 2 dims; got "
                f"shape {leaf.shape}"
            )

    n = st.size
    key = ("nag_layout", id(st.topology))
    layout = st._plan_cache.get(key)
    if layout is None:
        # Same circulant shift/slot decomposition the window subsystem uses
        # (one source of truth; windows._GraphLayout). -1 marks "no edge on
        # this shift for this rank" for the exchange body's active check.
        from .windows import _GraphLayout

        lay = _GraphLayout(st.topology, n)
        indeg = [lay.in_nbrs[r] for r in range(n)]
        d_max = max((len(v) for v in indeg), default=0)
        slot = np.where(lay.has_edge, lay.slot, -1).astype(np.int32)
        layout = (indeg, d_max, lay.shifts, slot)
        st._plan_cache[key] = layout
    indeg, d_max, shifts, slot = layout
    regular = len({len(v) for v in indeg}) == 1

    def finalize(padded, x):
        # [n, d_max, b, ...] -> sorted-neighbor concat per rank.
        flat = padded.reshape((n, d_max * x.shape[1]) + x.shape[2:])
        if regular:
            return flat
        return [flat[r, : len(indeg[r]) * x.shape[1]] for r in range(n)]

    with timeline_context(op_name, "NEIGHBOR_ALLGATHER"):
        if d_max == 0:
            out = jax.tree_util.tree_map(
                lambda x: [jnp.zeros((0,) + x.shape[2:], x.dtype)
                           for _ in range(n)],
                tensor,
            )
        else:
            leaves, treedef = jax.tree_util.tree_flatten(tensor)
            fn = _gather_exchange_fn(st.mesh, shifts, n, d_max)
            padded = fn(slot, tuple(leaves))
            out = jax.tree_util.tree_unflatten(
                treedef, [finalize(p, x) for p, x in zip(padded, leaves)]
            )
    return _handles.allocate(op_name, out)
