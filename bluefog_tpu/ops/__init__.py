"""Collective, neighbor, and window ops over the rank mesh."""

from .collectives import (
    allgather,
    allgather_nonblocking,
    allgather_v,
    allgather_v_nonblocking,
    allreduce,
    allreduce_nonblocking,
    allreduce_,
    allreduce_nonblocking_,
    barrier,
    broadcast,
    broadcast_nonblocking,
    broadcast_,
    broadcast_nonblocking_,
    pair_gossip,
    pair_gossip_nonblocking,
)
from .neighbors import (
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
)
from .plan import CombinePlan, apply_plan, rank_sharding, shard_rank_stacked
from .windows import (
    get_win_version,
    turn_off_win_ops_with_associated_p,
    turn_on_win_ops_with_associated_p,
    win_accumulate,
    win_accumulate_nonblocking,
    win_associated_p,
    win_associated_p_all,
    win_create,
    win_free,
    win_get,
    win_get_nonblocking,
    win_lock,
    win_mutex,
    win_poll,
    win_put,
    win_put_nonblocking,
    win_update,
    win_update_then_collect,
    win_wait,
)
