"""Pytree fusion: pack many small leaves into one flat exchange buffer.

Analog of BlueFog's tensor-fusion buffer (reference: FusionBufferManager,
tensor_queue.cc:127-155; fused neighbor-allreduce layout comment,
mpi_controller.cc:604-609). Within one jitted step XLA already fuses
collectives it can prove adjacent, but optimizer-level parameter averaging
wants *one* ppermute per step over a single flat buffer instead of one per
parameter leaf — fewer collective launches, full ICI packet utilization.

``pack`` flattens a pytree of rank-stacked [n, ...] leaves into a single
[n, total] buffer (casting to the widest needed dtype); ``unpack`` restores
the original structure. Both are jit-friendly (static shapes from the spec).
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class PackSpec(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]   # per-leaf shape without the rank dim
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]
    total: int
    buffer_dtype: Any


def make_spec(tree, rank_stacked: bool = True) -> PackSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = []
    dtypes = []
    sizes = []
    for leaf in leaves:
        shape = tuple(leaf.shape[1:]) if rank_stacked else tuple(leaf.shape)
        shapes.append(shape)
        dtypes.append(leaf.dtype)
        sizes.append(int(np.prod(shape)) if shape else 1)
    offsets = []
    off = 0
    for s in sizes:
        offsets.append(off)
        off += s
    # One buffer dtype for the whole exchange: promote to the widest float.
    buffer_dtype = jnp.result_type(*dtypes) if dtypes else jnp.float32
    return PackSpec(
        treedef, tuple(shapes), tuple(dtypes), tuple(offsets), tuple(sizes),
        off, buffer_dtype,
    )


def pack(tree, spec: PackSpec):
    """[n, ...] leaves -> [n, total] flat buffer (or [total] if unstacked)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = [
        leaf.reshape(leaf.shape[0], -1).astype(spec.buffer_dtype)
        for leaf in leaves
    ]
    return jnp.concatenate(flat, axis=1)


def unpack(buffer, spec: PackSpec):
    """[n, total] -> original pytree of [n, ...] leaves."""
    n = buffer.shape[0]
    leaves = []
    for shape, dtype, off, size in zip(spec.shapes, spec.dtypes, spec.offsets,
                                       spec.sizes):
        chunk = jax.lax.dynamic_slice_in_dim(buffer, off, size, axis=1)
        leaves.append(chunk.reshape((n,) + shape).astype(dtype))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def unpack_row(row: np.ndarray, spec: PackSpec,
               codec=None) -> List[np.ndarray]:
    """Host-side unpack of ONE rank's flat [total] row into per-leaf arrays.

    The elastic-rejoin state transfer moves a single rank's packed window
    row between controllers as host bytes; a jitted :func:`unpack` would
    need every controller to dispatch the same program — exactly what a
    one-sided rejoin cannot ask for — so this unpacks with numpy only.

    ``codec`` (an ``ops.codec.WireCodec``): ``row`` is an encoded wire
    payload; decode it back to the flat buffer-dtype row first — the
    inverse of :func:`pack_row`'s encode hook.
    """
    if codec is not None:
        row = codec.decode(np.asarray(row).reshape(-1).view(np.uint8),
                           np.dtype(spec.buffer_dtype), spec.total)
    row = np.asarray(row).reshape(-1)
    out: List[np.ndarray] = []
    for shape, dtype, off, size in zip(spec.shapes, spec.dtypes, spec.offsets,
                                       spec.sizes):
        out.append(np.asarray(row[off:off + size]).reshape(shape).astype(
            np.dtype(dtype)))
    return out


def pack_row(leaf_rows: Sequence, spec: PackSpec, codec=None) -> np.ndarray:
    """Host-side inverse of :func:`unpack_row`: per-leaf arrays for ONE
    rank -> that rank's flat [total] packed row (buffer dtype).

    ``codec`` (an ``ops.codec.WireCodec``): additionally encode the flat
    row into the codec's wire payload (uint8) — the insertion point the
    compressed gossip wire uses for whole-row host-side transforms
    (docs/compression.md); the deposit hot path in ``ops/windows.py``
    calls the codec on its already-flat rows directly.
    """
    bt = np.dtype(spec.buffer_dtype)
    row = np.concatenate([
        np.asarray(x).reshape(-1).astype(bt) for x in leaf_rows
    ]) if leaf_rows else np.zeros((0,), bt)
    if codec is not None:
        return codec.encode(row)
    return row


@functools.lru_cache(maxsize=512)
def _pack_compiled(spec: PackSpec):
    return jax.jit(lambda tree: pack(tree, spec))


@functools.lru_cache(maxsize=512)
def _unpack_compiled(spec: PackSpec):
    return jax.jit(lambda buf: unpack(buf, spec))


def pack_jit(tree, spec: PackSpec):
    """``pack`` through a per-spec cached jit (one program per buffer shape)."""
    return _pack_compiled(spec)(tree)


def unpack_jit(buffer, spec: PackSpec):
    return _unpack_compiled(spec)(buffer)


def group_leaves(leaves: Sequence, threshold_bytes: int,
                 rank_stacked: bool = True) -> List[List[int]]:
    """Greedy in-order batching of leaf indices into fusion groups.

    The analog of the reference's fusion buffer policy: consecutive tensors
    share one exchange buffer up to ``tensor_fusion_threshold`` bytes
    (tensor_queue.cc:127-155; fused layout mpi_controller.cc:604-609). The
    threshold counts PER-RANK bytes (the reference's buffer is per process),
    so ``rank_stacked`` leaves drop their leading rank dim from the tally.
    ``threshold_bytes <= 0`` disables fusion (one leaf per group). Groups
    never mix dtypes — packing would silently promote.
    """
    if threshold_bytes <= 0:
        return [[i] for i in range(len(leaves))]
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        shape = leaf.shape[1:] if rank_stacked else leaf.shape
        b = int(np.prod(shape)) * jnp.dtype(leaf.dtype).itemsize
        if cur and (cur_bytes + b > threshold_bytes or leaf.dtype != cur_dtype):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
        cur_dtype = leaf.dtype
    if cur:
        groups.append(cur)
    return groups
