"""Pytree fusion: pack many small leaves into one flat exchange buffer.

Analog of BlueFog's tensor-fusion buffer (reference: FusionBufferManager,
tensor_queue.cc:127-155; fused neighbor-allreduce layout comment,
mpi_controller.cc:604-609). Within one jitted step XLA already fuses
collectives it can prove adjacent, but optimizer-level parameter averaging
wants *one* ppermute per step over a single flat buffer instead of one per
parameter leaf — fewer collective launches, full ICI packet utilization.

``pack`` flattens a pytree of rank-stacked [n, ...] leaves into a single
[n, total] buffer (casting to the widest needed dtype); ``unpack`` restores
the original structure. Both are jit-friendly (static shapes from the spec).

**Shard dimension** (ISSUE r17, docs/sharded_windows.md): a spec built
with ``shard=ShardSpec`` additionally knows how the leaf list splits into
``S`` shards (``ops.partition``'s resolved piece table). ``pack_shard``
extracts ONE shard's pieces into a fixed ``[n, row_len]`` row (zero-padded
to the largest shard, so one window shape carries every shard in
rotation); ``scatter_shard`` writes a combined shard row back into the
full leaves — both compiled per (spec, shard) like pack/unpack. The
host-side ``pack_row``/``assemble_rows`` mirror the same piece table for
the one-sided paths (rejoin state transfer, donor reads) that cannot
dispatch a program. ``shard=None`` keeps every byte of the legacy layout.
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import partition as _partition
from ..runtime.config import knob_env


class PackSpec(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]   # per-leaf shape without the rank dim
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]
    total: int
    buffer_dtype: Any
    # resolved shard partition (ops.partition.ShardSpec) or None — the
    # default keeps the legacy single-row layout byte for byte
    shard: Optional[_partition.ShardSpec] = None


def make_spec(tree, rank_stacked: bool = True,
              shard: Optional[_partition.ShardSpec] = None) -> PackSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = []
    dtypes = []
    sizes = []
    for leaf in leaves:
        shape = tuple(leaf.shape[1:]) if rank_stacked else tuple(leaf.shape)
        shapes.append(shape)
        dtypes.append(leaf.dtype)
        sizes.append(int(np.prod(shape)) if shape else 1)
    offsets = []
    off = 0
    for s in sizes:
        offsets.append(off)
        off += s
    # One buffer dtype for the whole exchange: promote to the widest float.
    buffer_dtype = jnp.result_type(*dtypes) if dtypes else jnp.float32
    return PackSpec(
        treedef, tuple(shapes), tuple(dtypes), tuple(offsets), tuple(sizes),
        off, buffer_dtype, shard,
    )


def pack(tree, spec: PackSpec):
    """[n, ...] leaves -> [n, total] flat buffer (or [total] if unstacked)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = [
        leaf.reshape(leaf.shape[0], -1).astype(spec.buffer_dtype)
        for leaf in leaves
    ]
    return jnp.concatenate(flat, axis=1)


def unpack(buffer, spec: PackSpec):
    """[n, total] -> original pytree of [n, ...] leaves."""
    n = buffer.shape[0]
    leaves = []
    for shape, dtype, off, size in zip(spec.shapes, spec.dtypes, spec.offsets,
                                       spec.sizes):
        chunk = jax.lax.dynamic_slice_in_dim(buffer, off, size, axis=1)
        leaves.append(chunk.reshape((n,) + shape).astype(dtype))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def unpack_row(row: np.ndarray, spec: PackSpec,
               codec=None) -> List[np.ndarray]:
    """Host-side unpack of ONE rank's flat [total] row into per-leaf arrays.

    The elastic-rejoin state transfer moves a single rank's packed window
    row between controllers as host bytes; a jitted :func:`unpack` would
    need every controller to dispatch the same program — exactly what a
    one-sided rejoin cannot ask for — so this unpacks with numpy only.

    ``codec`` (an ``ops.codec.WireCodec``): ``row`` is an encoded wire
    payload; decode it back to the flat buffer-dtype row first — the
    inverse of :func:`pack_row`'s encode hook.
    """
    if codec is not None:
        row = codec.decode(np.asarray(row).reshape(-1).view(np.uint8),
                           np.dtype(spec.buffer_dtype), spec.total)
    row = np.asarray(row).reshape(-1)
    out: List[np.ndarray] = []
    for shape, dtype, off, size in zip(spec.shapes, spec.dtypes, spec.offsets,
                                       spec.sizes):
        out.append(np.asarray(row[off:off + size]).reshape(shape).astype(
            np.dtype(dtype)))
    return out


def pack_row(leaf_rows: Sequence, spec: PackSpec, codec=None,
             shard: Optional[int] = None) -> np.ndarray:
    """Host-side inverse of :func:`unpack_row`: per-leaf arrays for ONE
    rank -> that rank's flat [total] packed row (buffer dtype).

    ``codec`` (an ``ops.codec.WireCodec``): additionally encode the flat
    row into the codec's wire payload (uint8) — the insertion point the
    compressed gossip wire uses for whole-row host-side transforms
    (docs/compression.md); the deposit hot path in ``ops/windows.py``
    calls the codec on its already-flat rows directly.

    ``shard`` (sharded specs only): pack shard ``shard``'s pieces instead
    of the whole tree — a flat ``[spec.shard.row_len]`` row, zero-padded
    past the shard's own total so every shard frames to one window shape.
    """
    bt = np.dtype(spec.buffer_dtype)
    if shard is not None:
        sh = spec.shard
        if sh is None:
            raise ValueError("pack_row(shard=...) needs a sharded spec "
                             "(make_spec(..., shard=ShardSpec))")
        row = np.zeros((sh.row_len,), bt)
        off = 0
        for piece in sh.pieces[shard]:
            i, ax, a, b = piece
            leaf = np.asarray(leaf_rows[i])
            part = leaf if ax < 0 else \
                leaf[(slice(None),) * ax + (slice(a, b),)]
            flat = np.ascontiguousarray(part).reshape(-1).astype(
                bt, copy=False)
            row[off:off + flat.size] = flat
            off += flat.size
        if codec is not None:
            return codec.encode(row)
        return row
    row = np.concatenate([
        np.asarray(x).reshape(-1).astype(bt) for x in leaf_rows
    ]) if leaf_rows else np.zeros((0,), bt)
    if codec is not None:
        return codec.encode(row)
    return row


def assemble_rows(shard_rows: Sequence[np.ndarray], spec: PackSpec,
                  codec=None) -> List[np.ndarray]:
    """Reassemble ONE rank's full per-leaf arrays from all S shard rows
    (each the padded ``[row_len]`` flat row :func:`pack_row` produced —
    the shape published rows and donor transfers carry). The host-side
    inverse of the rotation: the rejoin path collects a donor's shards
    over S gossip steps and rebuilds the tree here, with no compiled
    dispatch (one-sided, like :func:`unpack_row`)."""
    sh = spec.shard
    if sh is None:
        raise ValueError("assemble_rows needs a sharded spec")
    if len(shard_rows) != sh.factor:
        raise ValueError(
            f"assemble_rows: got {len(shard_rows)} shard rows for a "
            f"factor-{sh.factor} spec")
    out = [np.zeros(shape, np.dtype(dt))
           for shape, dt in zip(spec.shapes, spec.dtypes)]
    for s in range(sh.factor):
        row = shard_rows[s]
        if codec is not None:
            row = codec.decode(
                np.asarray(row).reshape(-1).view(np.uint8),
                np.dtype(spec.buffer_dtype), sh.row_len)
        row = np.asarray(row).reshape(-1)
        off = 0
        for piece in sh.pieces[s]:
            i, ax, a, b = piece
            shape = _partition.piece_shape(spec.shapes[i], piece)
            size = int(np.prod(shape)) if shape else 1
            part = row[off:off + size].reshape(shape).astype(
                np.dtype(spec.dtypes[i]))
            if ax < 0:
                out[i][...] = part
            else:
                out[i][(slice(None),) * ax + (slice(a, b),)] = part
            off += size
    return out


def pack_shard(tree, spec: PackSpec, shard: int):
    """Rank-stacked leaves -> this shard's ``[n, row_len]`` padded row
    (the compiled intra-host "shard" half of the FSDP-style rotation:
    per-rank slicing only, no cross-rank movement — under a rank-sharded
    jit this lowers to a per-device gather, exactly the r13 local-mesh
    discipline)."""
    sh = spec.shard
    leaves = jax.tree_util.tree_leaves(tree)
    n = leaves[0].shape[0] if leaves else 0
    bt = spec.buffer_dtype
    flats = []
    got = 0
    for piece in sh.pieces[shard]:
        i, ax, a, b = piece
        leaf = leaves[i]
        part = leaf if ax < 0 else jax.lax.slice_in_dim(
            leaf, a, b, axis=ax + 1)
        flats.append(part.reshape(n, -1).astype(bt))
        got += flats[-1].shape[1]
    pad = sh.row_len - got
    if pad:
        flats.append(jnp.zeros((n, pad), bt))
    return jnp.concatenate(flats, axis=1) if flats else \
        jnp.zeros((n, sh.row_len), bt)


def scatter_shard(leaves: Sequence, buf, spec: PackSpec, shard: int):
    """The gather half: write a combined ``[n, row_len]`` shard row back
    into the full rank-stacked leaves (only this shard's pieces change;
    the pad tail is ignored). Returns the new leaf list."""
    sh = spec.shard
    out = list(leaves)
    n = buf.shape[0]
    off = 0
    for piece in sh.pieces[shard]:
        i, ax, a, b = piece
        shape = _partition.piece_shape(spec.shapes[i], piece)
        size = int(np.prod(shape)) if shape else 1
        chunk = jax.lax.dynamic_slice_in_dim(buf, off, size, axis=1)
        chunk = chunk.reshape((n,) + shape).astype(out[i].dtype)
        if ax < 0:
            out[i] = chunk
        else:
            idx = (slice(None),) * (ax + 1) + (slice(a, b),)
            out[i] = out[i].at[idx].set(chunk)
        off += size
    return out


@functools.lru_cache(maxsize=512)
def _pack_shard_compiled(spec: PackSpec, shard: int):
    return jax.jit(lambda tree: pack_shard(tree, spec, shard))


@functools.lru_cache(maxsize=512)
def _scatter_shard_compiled(spec: PackSpec, shard: int, donate: bool):
    # Donating the leaves lets XLA update the touched pieces in place
    # instead of double-buffering the full model — the whole point of
    # shard-sized gossip memory (the rlimit acceptance demo fails
    # without it). The donated leaves are the live param buffers, so the
    # default-on donation is an ALIASING CONTRACT on the optimizer step
    # (docs/sharded_windows.md): after a sharded gossip step, arrays
    # reached through any retained pre-step TrainState are invalidated.
    # Callers that keep such aliases (an eval/checkpoint copy of the
    # previous state) opt out via BLUEFOG_WIN_SHARD_DONATE=0, paying the
    # transient double-buffer the unsharded unpack path always pays. The
    # shard buffer is NOT donated: its shape aliases no output, so
    # donation would only warn.
    return jax.jit(
        lambda leaves, buf: tuple(scatter_shard(leaves, buf, spec, shard)),
        donate_argnums=(0,) if donate else ())


def pack_shard_jit(tree, spec: PackSpec, shard: int):
    """``pack_shard`` through a per-(spec, shard) cached jit."""
    return _pack_shard_compiled(spec, shard)(tree)


def scatter_shard_jit(leaves, buf, spec: PackSpec, shard: int):
    donate = bool(knob_env("BLUEFOG_WIN_SHARD_DONATE"))
    return _scatter_shard_compiled(spec, shard, donate)(tuple(leaves), buf)


@functools.lru_cache(maxsize=512)
def _pack_compiled(spec: PackSpec):
    return jax.jit(lambda tree: pack(tree, spec))


@functools.lru_cache(maxsize=512)
def _unpack_compiled(spec: PackSpec):
    return jax.jit(lambda buf: unpack(buf, spec))


def pack_jit(tree, spec: PackSpec):
    """``pack`` through a per-spec cached jit (one program per buffer shape)."""
    return _pack_compiled(spec)(tree)


def unpack_jit(buffer, spec: PackSpec):
    return _unpack_compiled(spec)(buffer)


def group_leaves(leaves: Sequence, threshold_bytes: int,
                 rank_stacked: bool = True) -> List[List[int]]:
    """Greedy in-order batching of leaf indices into fusion groups.

    The analog of the reference's fusion buffer policy: consecutive tensors
    share one exchange buffer up to ``tensor_fusion_threshold`` bytes
    (tensor_queue.cc:127-155; fused layout mpi_controller.cc:604-609). The
    threshold counts PER-RANK bytes (the reference's buffer is per process),
    so ``rank_stacked`` leaves drop their leading rank dim from the tally.
    ``threshold_bytes <= 0`` disables fusion (one leaf per group). Groups
    never mix dtypes — packing would silently promote.
    """
    if threshold_bytes <= 0:
        return [[i] for i in range(len(leaves))]
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        shape = leaf.shape[1:] if rank_stacked else leaf.shape
        b = int(np.prod(shape)) * jnp.dtype(leaf.dtype).itemsize
        if cur and (cur_bytes + b > threshold_bytes or leaf.dtype != cur_dtype):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
        cur_dtype = leaf.dtype
    if cur:
        groups.append(cur)
    return groups
