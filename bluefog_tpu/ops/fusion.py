"""Pytree fusion: pack many small leaves into one flat exchange buffer.

Analog of BlueFog's tensor-fusion buffer (reference: FusionBufferManager,
tensor_queue.cc:127-155; fused neighbor-allreduce layout comment,
mpi_controller.cc:604-609). Within one jitted step XLA already fuses
collectives it can prove adjacent, but optimizer-level parameter averaging
wants *one* ppermute per step over a single flat buffer instead of one per
parameter leaf — fewer collective launches, full ICI packet utilization.

``pack`` flattens a pytree of rank-stacked [n, ...] leaves into a single
[n, total] buffer (casting to the widest needed dtype); ``unpack`` restores
the original structure. Both are jit-friendly (static shapes from the spec).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class PackSpec(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]   # per-leaf shape without the rank dim
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]
    total: int
    buffer_dtype: Any


def make_spec(tree, rank_stacked: bool = True) -> PackSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = []
    dtypes = []
    sizes = []
    for leaf in leaves:
        shape = tuple(leaf.shape[1:]) if rank_stacked else tuple(leaf.shape)
        shapes.append(shape)
        dtypes.append(leaf.dtype)
        sizes.append(int(np.prod(shape)) if shape else 1)
    offsets = []
    off = 0
    for s in sizes:
        offsets.append(off)
        off += s
    # One buffer dtype for the whole exchange: promote to the widest float.
    buffer_dtype = jnp.result_type(*dtypes) if dtypes else jnp.float32
    return PackSpec(
        treedef, tuple(shapes), tuple(dtypes), tuple(offsets), tuple(sizes),
        off, buffer_dtype,
    )


def pack(tree, spec: PackSpec):
    """[n, ...] leaves -> [n, total] flat buffer (or [total] if unstacked)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = [
        leaf.reshape(leaf.shape[0], -1).astype(spec.buffer_dtype)
        for leaf in leaves
    ]
    return jnp.concatenate(flat, axis=1)


def unpack(buffer, spec: PackSpec):
    """[n, total] -> original pytree of [n, ...] leaves."""
    n = buffer.shape[0]
    leaves = []
    for shape, dtype, off, size in zip(spec.shapes, spec.dtypes, spec.offsets,
                                       spec.sizes):
        chunk = jax.lax.dynamic_slice_in_dim(buffer, off, size, axis=1)
        leaves.append(chunk.reshape((n,) + shape).astype(dtype))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)
