"""Partition rules: regex rules → per-leaf shard cuts for sharded windows.

The sharded window plane (ISSUE r17, docs/sharded_windows.md) packs a
rank's gossip row as ONE SHARD of the parameter tree instead of the full
tree, so every win-op wire payload, mailbox slot, and published row
shrinks by the shard factor. This module is the layer that decides HOW a
pytree splits into ``S`` shards:

* :func:`match_partition_rules` — the SNIPPETS-shape rule matcher: an
  ordered list of ``(regex, axis_spec)`` pairs applied to ``/``-joined
  leaf path names; first match wins. ``axis_spec`` is an axis index,
  ``"largest"`` (shard the leaf's largest axis — the ``auto`` rule), or
  ``"none"`` (never split this leaf).
* :func:`build_shard_spec` — resolves the per-leaf decisions into a
  :class:`ShardSpec`: an explicit, hashable piece table (leaf, axis,
  start, stop) per shard. Leaves below the size floor (or whose chosen
  axis is shorter than ``S``) are never cut; they are greedily assigned
  whole to the lightest shard so shard totals stay balanced.

The spec is resolved ONCE at window creation (the analog of
``match_partition_rules`` → per-param ``PartitionSpec`` over a named mesh
in the exemplars) and then rides ``ops.fusion.PackSpec`` — every
pack/unpack, wire payload, and rejoin reassembly derives from the same
piece table, so shard boundaries can never drift between controllers
that resolved the same rules over the same tree.
"""

from __future__ import annotations

import re
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax

from ..runtime.logging import logger

# one piece of one shard's packed row: elements [start, stop) of `axis`
# of leaf `leaf` (axis=-1 ⇒ the whole, uncut leaf)
Piece = Tuple[int, int, int, int]  # (leaf, axis, start, stop)


class ShardSpec(NamedTuple):
    """Resolved partition of a leaf list into ``factor`` shards.

    ``pieces[s]`` lists shard ``s``'s pieces in leaf order; ``totals[s]``
    is its element count; ``row_len`` is ``max(totals)`` — the padded
    length every shard's packed row is framed to, so ONE window (one
    fixed row shape) carries every shard in rotation. Hashable by
    construction: it keys the compiled pack/scatter program caches.
    """

    factor: int
    pieces: Tuple[Tuple[Piece, ...], ...]
    totals: Tuple[int, ...]
    row_len: int


def leaf_names(tree) -> List[str]:
    """``/``-joined path names for the tree's leaves, in flatten order
    (the names the partition-rule regexes match against)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, _ in flat:
        parts = []
        for p in path:
            key = getattr(p, "key", getattr(p, "name",
                                            getattr(p, "idx", None)))
            parts.append(str(key))
        out.append("/".join(parts) if parts else "")
    return out


def parse_rules(spec: Optional[str]):
    """``BLUEFOG_WIN_SHARD_RULES`` grammar → ordered ``(regex, axis)``.

    Comma-separated ``regex=axis`` terms; ``axis`` is an integer axis
    index, ``largest``, or ``none``. A malformed term is skipped with a
    warning (a typo must degrade to the auto rule, never crash a job at
    window creation). Empty/None → ``[(".*", "largest")]`` (the auto
    rule: shard every eligible leaf's largest axis).
    """
    if not spec:
        return [(re.compile(".*"), "largest")]
    rules = []
    for term in str(spec).split(","):
        term = term.strip()
        if not term:
            continue
        if "=" not in term:
            logger.warning(
                "BLUEFOG_WIN_SHARD_RULES term %r is not regex=axis; "
                "skipping it", term)
            continue
        pat, _, ax = term.rpartition("=")
        ax = ax.strip().lower()
        if ax not in ("largest", "none"):
            try:
                ax = int(ax)
            except ValueError:
                logger.warning(
                    "BLUEFOG_WIN_SHARD_RULES axis %r is not an integer, "
                    "'largest', or 'none'; skipping %r", ax, term)
                continue
        try:
            rules.append((re.compile(pat.strip()), ax))
        except re.error as exc:
            logger.warning(
                "BLUEFOG_WIN_SHARD_RULES regex %r does not compile (%s); "
                "skipping it", pat, exc)
    rules.append((re.compile(".*"), "largest"))  # auto backstop
    return rules


def match_partition_rules(rules, names: Sequence[str],
                          shapes: Sequence[Tuple[int, ...]]):
    """Per-leaf axis decision: first rule whose regex ``search``es the
    leaf's path name wins (the SNIPPETS ``match_partition_rules`` shape).
    Returns a list of axis indices (or None for uncut). Scalars are
    never partitioned."""
    out: List[Optional[int]] = []
    for name, shape in zip(names, shapes):
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            out.append(None)
            continue
        ax: Optional[int] = None
        for pat, spec in rules:
            if pat.search(name) is None:
                continue
            if spec == "none":
                ax = None
            elif spec == "largest":
                ax = int(np.argmax(shape))
            else:
                ax = int(spec) if -len(shape) <= int(spec) < len(shape) \
                    else None
                if ax is not None and ax < 0:
                    ax += len(shape)
            break
        out.append(ax)
    return out


def _split_bounds(dim: int, factor: int) -> List[Tuple[int, int]]:
    """np.array_split boundaries: ``factor`` contiguous chunks of ``dim``
    (the first ``dim % factor`` chunks one longer)."""
    q, r = divmod(dim, factor)
    bounds = []
    off = 0
    for i in range(factor):
        n = q + (1 if i < r else 0)
        bounds.append((off, off + n))
        off += n
    return bounds


def build_shard_spec(shapes: Sequence[Tuple[int, ...]],
                     dtypes: Sequence,
                     factor: int,
                     names: Optional[Sequence[str]] = None,
                     rules_spec: Optional[str] = None,
                     floor_bytes: int = 0) -> ShardSpec:
    """Resolve the partition of a leaf list into ``factor`` shards.

    ``shapes`` are per-leaf shapes WITHOUT the rank dimension (the same
    convention as ``fusion.PackSpec.shapes``). Leaves smaller than
    ``floor_bytes`` — or whose chosen axis is shorter than ``factor`` —
    stay whole and are greedily packed onto the lightest shard, so tiny
    biases/norm scales never fragment into sub-cacheline wire pieces.
    """
    factor = max(1, int(factor))
    names = list(names) if names is not None else \
        [str(i) for i in range(len(shapes))]
    rules = parse_rules(rules_spec)
    axes = match_partition_rules(rules, names, shapes)
    pieces: List[List[Piece]] = [[] for _ in range(factor)]
    totals = np.zeros(factor, np.int64)
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        size = int(np.prod(shape)) if shape else 1
        ax = axes[i]
        nbytes = size * np.dtype(dtype).itemsize
        if factor == 1 or ax is None or nbytes < floor_bytes or \
                shape[ax] < factor:
            s = int(np.argmin(totals))
            pieces[s].append((i, -1, 0, size))
            totals[s] += size
            continue
        per = size // shape[ax]
        for s, (a, b) in enumerate(_split_bounds(shape[ax], factor)):
            pieces[s].append((i, ax, a, b))
            totals[s] += (b - a) * per
    return ShardSpec(
        factor,
        tuple(tuple(p) for p in pieces),
        tuple(int(t) for t in totals),
        int(totals.max()) if len(totals) else 0,
    )


def spec_for_tree(tree, factor: int, rules_spec: Optional[str] = None,
                  floor_bytes: int = 0, rank_stacked: bool = True
                  ) -> ShardSpec:
    """:func:`build_shard_spec` over a (rank-stacked) pytree."""
    leaves = jax.tree_util.tree_leaves(tree)
    shapes = [tuple(x.shape[1:]) if rank_stacked else tuple(x.shape)
              for x in leaves]
    dtypes = [x.dtype for x in leaves]
    return build_shard_spec(shapes, dtypes, factor,
                            names=leaf_names(tree),
                            rules_spec=rules_spec, floor_bytes=floor_bytes)


def piece_shape(shape: Tuple[int, ...], piece: Piece) -> Tuple[int, ...]:
    """The sub-array shape one piece selects out of a leaf of ``shape``."""
    _, ax, a, b = piece
    if ax < 0:
        return shape
    return shape[:ax] + (b - a,) + shape[ax + 1:]


def piece_size(shape: Tuple[int, ...], piece: Piece) -> int:
    sh = piece_shape(shape, piece)
    return int(np.prod(sh)) if sh else 1


__all__ = [
    "ShardSpec", "Piece", "leaf_names", "parse_rules",
    "match_partition_rules", "build_shard_spec", "spec_for_tree",
    "piece_shape", "piece_size",
]
