"""Wire codecs for the hosted gossip plane: shrink the deposit payload.

Compressed decentralized gossip (CHOCO-SGD, Koloskova et al.; EF-SGD,
Stich et al.) multiplies every MB/s the transport layers bought by
shrinking the wire itself: the r6 deposit format already ships payloads
in a *wire dtype* and folds them in a *wide dtype*, so inserting a codec
between ``pack_row`` and the ``_finish_deposit`` fold is a pure payload
transform — scalars (push-sum p, versions, mutexes) never compress.

Three codec families (``BLUEFOG_WIN_CODEC``, default ``none`` — the
legacy wire stays byte-identical and is test-pinned):

* ``int8``  — per-block symmetric quantization: each block of
  ``BLUEFOG_WIN_CODEC_BLOCK`` elements carries one f32 scale
  (``amax / 127``) and int8 codes. ~4x for f32 windows, ~2x for bf16.
* ``fp8``   — per-block scale to the float8_e4m3 grid (``amax / 448``)
  plus 1-byte codes; keeps ~3 mantissa bits where int8 keeps ~7 around
  the block max — better for heavy-tailed rows.
* ``topk:<frac>`` — top-k sparsification by magnitude (index+value
  records) with **error feedback**: the sender adds its residual before
  selecting, and keeps ``(input + residual) - decode(encode(...))`` for
  the next step, so dropped mass is delayed, never lost (the EF-SGD
  convergence argument). ``topk`` alone means ``topk:0.01``.

Every encoded payload is self-describing (block size / k ride the
payload, not the environment), so a cross-controller knob mismatch can
at worst produce a codec-id mismatch error, never a silent misparse.

Push-sum rule: codecs compress the NUMERATOR payload only; the
associated-p contribution ships exact in the deposit header (f64), so
``sum(mass) == sum(minted)`` gauges stay green under any codec. Top-k's
residual holds numerator mass *transiently* (it arrives on later
steps); quantization is per-deposit and unbiased up to rounding.

The compiled (ppermute) plane has no wire to shrink, but the
quantization codecs still apply *numerically* through
:func:`quantize_blend` in the mail-dtype blend, so a hybrid partition's
compiled and hosted edges see the same value grid. Top-k does not apply
there (a dense exchange has no index records).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..runtime.config import knob_env
from ..runtime.logging import logger

# codec ids: ride the deposit header's mode byte (high nibble), so id 0
# MUST mean "no codec" — the legacy mode byte is 0 (put) or 1 (acc) and
# stays byte-identical when no codec is configured.
CODEC_NONE = 0
CODEC_INT8 = 1
CODEC_FP8 = 2
CODEC_TOPK = 3

_F8_MAX = 448.0  # float8_e4m3 largest finite magnitude
_DEFAULT_TOPK_FRAC = 0.01


def _block_size() -> int:
    b = int(knob_env("BLUEFOG_WIN_CODEC_BLOCK") or 4096)
    return max(64, b)


def _f8_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.float8_e4m3fn)


def _as_f32_flat(arr) -> np.ndarray:
    return np.ascontiguousarray(arr).reshape(-1).astype(np.float32,
                                                        copy=False)


def _blocked(flat: np.ndarray, block: int):
    """(padded [nb, block] view, nb). Padding is zeros (quantizes to 0)."""
    n = flat.size
    nb = max(1, -(-n // block))
    pad = nb * block - n
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return flat.reshape(nb, block), nb


def _scales(blocks: np.ndarray, full: float) -> np.ndarray:
    """Per-block f32 scale mapping each block's amax onto ``full``.

    ``max(max, -min)`` instead of ``max(abs)``: two reduction passes
    that WRITE nothing, where ``np.abs`` materializes (and page-faults)
    a full row-sized temporary on the 100 MB encode hot path."""
    amax = np.maximum(blocks.max(axis=1), -blocks.min(axis=1))
    return (amax / full).astype(np.float32)


def _scale_inplace(x: np.ndarray, scale: np.ndarray, block: int,
                   count: int) -> None:
    """``x[i] *= scale[i // block]`` without materializing a repeated
    scale vector (the decode hot path runs at 100 MB row scale)."""
    nf = count // block
    if nf:
        x[:nf * block].reshape(nf, block)[...] *= scale[:nf, None]
    if count > nf * block:
        x[nf * block:] *= scale[nf]


class WireCodec:
    """One codec: flat wire-dtype row -> self-describing uint8 payload."""

    cid = CODEC_NONE
    name = "none"
    error_feedback = False
    # whether ABSOLUTE state (the published "exposed window" rows) may
    # ride this codec: true for the quantizers (a bounded-error dense
    # approximation), false for top-k (dropping coordinates from a state
    # snapshot would zero them for every reader)
    state_codec = False
    # static wire-bytes / raw-bytes estimate (f32 rows): what the plane
    # planner's size floor uses before measured attribution is ingested
    nominal_ratio = 1.0

    def encode(self, arr: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def decode(self, raw, dtype, count: int, scale_mul=None, out=None):
        """Decode ``raw`` back to ``count`` elements of ``dtype``.

        ``scale_mul``: fold a scalar (the deposit's edge weight) into the
        payload's own scale records — per-BLOCK work instead of a full
        per-element multiply pass. ``out``: decode straight into a
        caller-provided flat f32 buffer (the put-mode mailbox slot),
        skipping the intermediate row allocation entirely; returns
        ``out``. Both are pure hot-path levers — semantics match the
        plain form bit for bit for ``scale_mul=None``."""
        raise NotImplementedError


class Int8Codec(WireCodec):
    """Per-block symmetric int8: ``q = round(x * 127 / amax_block)``."""

    cid = CODEC_INT8
    name = "int8"
    state_codec = True
    nominal_ratio = 0.26  # 1/4 + per-block scale overhead

    def encode(self, arr) -> np.ndarray:
        flat = _as_f32_flat(arr)
        n = flat.size
        block = _block_size()
        b, nb = _blocked(flat, block)
        scale = _scales(b, 127.0)
        safe = np.where(scale > 0, scale, np.float32(1.0))
        # one temporary + two in-place passes; no clip needed — |b| <=
        # amax by construction, so |t| <= 127 + ulp and rint lands on
        # [-127, 127] exactly
        t = b * (np.float32(1.0) / safe)[:, None]
        np.rint(t, out=t)
        q = t.astype(np.int8)
        # exactly n code bytes on the wire — the tail block's padding
        # never ships (it would be 2x overhead for a just-over-one-block
        # row)
        out = np.empty(4 + 4 * nb + n, np.uint8)
        out[:4] = np.frombuffer(struct.pack("<I", block), np.uint8)
        out[4:4 + 4 * nb] = scale.view(np.uint8)
        out[4 + 4 * nb:] = q.reshape(-1)[:n].view(np.uint8)
        return out

    def decode(self, raw, dtype, count: int, scale_mul=None, out=None):
        raw = np.frombuffer(raw, np.uint8) if isinstance(
            raw, (bytes, bytearray, memoryview)) else raw.reshape(-1)
        block, = struct.unpack("<I", raw[:4].tobytes())
        nb = max(1, -(-count // block))
        scale = raw[4:4 + 4 * nb].view(np.float32)
        if scale_mul is not None and scale_mul != 1.0:
            scale = scale * np.float32(scale_mul)  # nb floats, not count
        q = raw[4 + 4 * nb:4 + 4 * nb + count].view(np.int8)
        if out is not None:
            if count == nb * block:
                # ONE fused pass: int8 * per-block scale straight into
                # the caller's buffer (the mailbox slot) — no cast copy
                np.multiply(q.reshape(nb, block), scale[:, None],
                            out=out.reshape(nb, block), casting="unsafe")
            else:
                np.copyto(out, q, casting="unsafe")  # int8 -> f32 cast
                _scale_inplace(out, scale, block, count)
            return out
        x = q.astype(np.float32)
        _scale_inplace(x, scale, block, count)
        return x.astype(dtype, copy=False)


class Fp8Codec(WireCodec):
    """Per-block scaled float8_e4m3: relative precision across the block."""

    cid = CODEC_FP8
    name = "fp8"
    state_codec = True
    nominal_ratio = 0.26

    def encode(self, arr) -> np.ndarray:
        flat = _as_f32_flat(arr)
        n = flat.size
        block = _block_size()
        b, nb = _blocked(flat, block)
        scale = _scales(b, _F8_MAX)
        safe = np.where(scale > 0, scale, np.float32(1.0))
        q = (b / safe[:, None]).astype(_f8_dtype())
        out = np.empty(4 + 4 * nb + n, np.uint8)
        out[:4] = np.frombuffer(struct.pack("<I", block), np.uint8)
        out[4:4 + 4 * nb] = scale.view(np.uint8)
        out[4 + 4 * nb:] = q.reshape(-1)[:n].view(np.uint8)
        return out

    def decode(self, raw, dtype, count: int, scale_mul=None, out=None):
        raw = np.frombuffer(raw, np.uint8) if isinstance(
            raw, (bytes, bytearray, memoryview)) else raw.reshape(-1)
        block, = struct.unpack("<I", raw[:4].tobytes())
        nb = max(1, -(-count // block))
        scale = raw[4:4 + 4 * nb].view(np.float32)
        if scale_mul is not None and scale_mul != 1.0:
            scale = scale * np.float32(scale_mul)
        q = raw[4 + 4 * nb:4 + 4 * nb + count].view(_f8_dtype())
        if out is not None:
            np.copyto(out, q.astype(np.float32), casting="unsafe")
            _scale_inplace(out, scale, block, count)
            return out
        x = q.astype(np.float32)
        _scale_inplace(x, scale, block, count)
        return x.astype(dtype, copy=False)


class TopKCodec(WireCodec):
    """Top-k by magnitude: ``u32 k | u32 idx[k] | f32 val[k]`` records.

    ``error_feedback=True``: the window plane keeps a residual per owned
    source row (``(input + residual) - decode(encode(input + residual))``)
    so the dropped coordinates are sent on later steps instead of lost —
    the property the convergence-parity oracle pins.
    """

    cid = CODEC_TOPK
    error_feedback = True

    def __init__(self, frac: float) -> None:
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk fraction must be in (0, 1], got {frac}")
        self.frac = float(frac)
        self.name = f"topk:{frac:g}"
        # u32 index + f32 value per kept element vs 4 raw bytes/element
        self.nominal_ratio = min(1.0, 2.0 * self.frac)

    def encode(self, arr) -> np.ndarray:
        flat = _as_f32_flat(arr)
        n = flat.size
        k = max(1, min(n, int(round(self.frac * n))))
        if k >= n:
            idx = np.arange(n, dtype=np.uint32)
        else:
            part = np.argpartition(np.abs(flat), n - k)[n - k:]
            idx = np.sort(part).astype(np.uint32)
        vals = flat[idx].astype(np.float32)
        out = np.empty(4 + 8 * k, np.uint8)
        out[:4] = np.frombuffer(struct.pack("<I", k), np.uint8)
        out[4:4 + 4 * k] = idx.view(np.uint8)
        out[4 + 4 * k:] = vals.view(np.uint8)
        return out

    def decode(self, raw, dtype, count: int, scale_mul=None, out=None):
        raw = np.frombuffer(raw, np.uint8) if isinstance(
            raw, (bytes, bytearray, memoryview)) else raw.reshape(-1)
        k, = struct.unpack("<I", raw[:4].tobytes())
        idx = raw[4:4 + 4 * k].view(np.uint32)
        vals = raw[4 + 4 * k:4 + 8 * k].view(np.float32)
        if k and int(idx.max()) >= count:
            raise ValueError(
                f"top-k deposit names index {int(idx.max())} beyond the "
                f"{count}-element row — mismatched window shape across "
                "controllers")
        if scale_mul is not None and scale_mul != 1.0:
            vals = vals * np.float32(scale_mul)  # k floats, not count
        if out is not None:
            out[:] = 0.0
            out[idx] = vals
            return out
        dense = np.zeros(count, np.float32)
        dense[idx] = vals
        return dense.astype(dtype, copy=False)


_warned_bad_spec = set()


def resolve(spec) -> Optional[WireCodec]:
    """``BLUEFOG_WIN_CODEC`` value -> codec instance (None = legacy wire).

    Grammar: ``none | int8 | fp8 | topk:<frac> | topk``. An unknown spec
    warns once and falls back to ``none`` — a typo must degrade to the
    exact legacy wire, never to a half-configured codec.
    """
    if not spec:
        return None
    s = str(spec).strip().lower()
    if s in ("", "none", "0"):
        return None
    if s == "int8":
        return Int8Codec()
    if s == "fp8":
        return Fp8Codec()
    if s == "topk":
        return TopKCodec(_DEFAULT_TOPK_FRAC)
    if s.startswith("topk:"):
        try:
            return TopKCodec(float(s.split(":", 1)[1]))
        except ValueError:
            pass
    if s not in _warned_bad_spec:
        _warned_bad_spec.add(s)
        logger.warning(
            "BLUEFOG_WIN_CODEC=%r is not none|int8|fp8|topk:<frac>; "
            "running the uncompressed wire", spec)
    return None


def resolve_edge_spec(spec) -> Tuple[Optional[WireCodec],
                                     Dict[Tuple[int, int],
                                          Optional[WireCodec]]]:
    """Per-edge ``BLUEFOG_WIN_CODEC`` grammar -> (base codec, overrides).

    Grammar: ``<spec>(;<src>><dst>=<spec>)*`` where ``<spec>`` is the
    single-codec grammar :func:`resolve` accepts. The first term is the
    window-wide base codec; each following term pins ONE directed edge to
    its own codec (``=`` separates the edge from the spec because
    ``topk:<frac>`` already uses ``:``). Example::

        BLUEFOG_WIN_CODEC='none;0>1=int8;2>3=topk:0.01'

    A malformed edge term warns once and is skipped — same degrade-to-
    legacy contract as :func:`resolve`. A bare single-codec spec returns
    ``(codec, {})``, so every existing config parses unchanged.
    """
    if not spec:
        return None, {}
    parts = str(spec).split(";")
    base = resolve(parts[0])
    overrides: Dict[Tuple[int, int], Optional[WireCodec]] = {}
    for term in parts[1:]:
        term = term.strip()
        if not term:
            continue
        head, sep, sub = term.partition("=")
        ok = bool(sep)
        if ok:
            try:
                src_s, dst_s = head.split(">", 1)
                edge = (int(src_s), int(dst_s))
            except ValueError:
                ok = False
        if not ok:
            key = f"edge:{term}"
            if key not in _warned_bad_spec:
                _warned_bad_spec.add(key)
                logger.warning(
                    "BLUEFOG_WIN_CODEC: skipping malformed per-edge term "
                    "%r (grammar: <spec>;<src>><dst>=<spec>;...)", term)
            continue
        overrides[edge] = resolve(sub)
    return base, overrides


def state_codec_for(codec: Optional[WireCodec]) -> Optional[WireCodec]:
    """The codec a window publishes its ABSOLUTE state rows under.

    Quantizers publish through themselves (bounded-error dense state).
    Top-k — whose sparse records cannot carry absolute state — used to
    publish RAW rows, which made the win_get/pull leg pay full bytes
    under the one codec that compresses the deposit wire hardest (ISSUE
    r17 satellite); it now falls back to int8 absolute-state payloads
    behind the same ``_parse_published`` magic framing (the reader
    dispatches on the payload's own codec id, so no reader changes).
    ``None`` (codec off) keeps the raw legacy publish byte-identical.
    """
    if codec is None:
        return None
    if codec.state_codec:
        return codec
    return Int8Codec()


def by_id(cid: int) -> WireCodec:
    """Decode-side lookup: the drain learns the codec from the deposit
    header (codec id in the mode byte's high nibble), never from its own
    environment — origin and owner env may disagree. Top-k decode needs
    no fraction (k rides the payload), so a parameterless instance
    suffices."""
    if cid == CODEC_INT8:
        return Int8Codec()
    if cid == CODEC_FP8:
        return Fp8Codec()
    if cid == CODEC_TOPK:
        return TopKCodec(_DEFAULT_TOPK_FRAC)
    raise ValueError(f"unknown wire codec id {cid} in deposit header — "
                     "origin runs a newer codec than this build")


def quantize_blend(x, cid: int):
    """In-program (jax) analog of the quantization codecs for the
    compiled plane's mail-dtype blend: per-tensor symmetric scale, the
    same int8 / fp8 grids the hosted wire ships. Identity for ``none``
    and for top-k (no dense-exchange analog)."""
    if cid not in (CODEC_INT8, CODEC_FP8):
        return x
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    a = jnp.max(jnp.abs(xf))
    if cid == CODEC_INT8:
        s = jnp.where(a > 0, a / 127.0, 1.0)
        q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
        return (q.astype(jnp.float32) * s).astype(x.dtype)
    s = jnp.where(a > 0, a / jnp.float32(_F8_MAX), 1.0)
    q = (xf / s).astype(jnp.float8_e4m3fn)
    return (q.astype(jnp.float32) * s).astype(x.dtype)


__all__: List[str] = [
    "CODEC_NONE", "CODEC_INT8", "CODEC_FP8", "CODEC_TOPK",
    "WireCodec", "Int8Codec", "Fp8Codec", "TopKCodec",
    "resolve", "resolve_edge_spec", "by_id", "state_codec_for",
    "quantize_blend",
]
