"""Batch-norm folding for inference: absorb BN into conv weights + bias.

The classic serving transform (torch's ``fuse_conv_bn_eval``; the
reference's deployment story inherits it from torchvision): at inference a
BatchNorm is the affine ``y = (x - mean) * gamma / sqrt(var + eps) + beta``
— fold the scale into the preceding conv's output channels and the shift
into a bias, and the norm disappears from the graph entirely. Use with the
``fold_bn=True`` model variant::

    folded = fold_batchnorm(params, batch_stats)
    model = ResNet50(dtype=jnp.bfloat16, fold_bn=True)
    logits = model.apply({"params": folded}, x, train=False)

Measured on the v5e chip this is a wash for THROUGHPUT — 11.71 ms/step
unfolded vs 12.25 ms folded at B=128, because XLA already fuses the
inference-BN affine into the conv epilogue (see PERF.md) — but it halves
the inference param-collection count (no batch_stats to ship) and keeps
the exported graph free of normalization ops, which is what serving
runtimes want.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

_EPS = 1e-5  # must match the model's BatchNorm epsilon


def _fold_pair(kernel, scale, bias, mean, var,
               eps: float) -> Tuple[Any, Any]:
    """(W', b') for conv kernel [kh, kw, cin, cout] + BN stats over cout."""
    g = np.asarray(scale, np.float64)
    b = np.asarray(bias, np.float64)
    mu = np.asarray(mean, np.float64)
    v = np.asarray(var, np.float64)
    inv = g / np.sqrt(v + eps)
    w = np.asarray(kernel, np.float64) * inv  # broadcast over cout (last)
    bnew = b - mu * inv
    return (jnp.asarray(w, jnp.float32), jnp.asarray(bnew, jnp.float32))


def _norm_to_conv_name(norm_name: str, siblings) -> str:
    """Which conv a BN folds into, by the model zoo's naming contract."""
    if norm_name.startswith("BatchNorm_"):
        return "Conv_" + norm_name.split("_", 1)[1]
    if norm_name == "norm_proj":
        return "conv_proj"
    if norm_name == "bn_init":
        for cand in ("conv_init", "conv_init_s2d"):
            if cand in siblings:
                return cand
    raise ValueError(f"no conv pairing rule for norm '{norm_name}'")


def fold_batchnorm(params: Dict, batch_stats: Dict,
                   eps: float = _EPS) -> Dict:
    """Fold every BatchNorm in ``params`` into its preceding conv.

    Returns a new param tree for the ``fold_bn=True`` model variant: BN
    entries are gone, each paired conv gains a ``bias``. Pairing follows
    the model zoo's naming (``BatchNorm_i`` -> ``Conv_i``, ``norm_proj`` ->
    ``conv_proj``, ``bn_init`` -> the stem conv); unknown norm names raise
    rather than silently passing through un-folded.
    """
    def walk(p: Dict, s: Dict) -> Dict:
        out = {}
        norm_shaped = [k for k, v in p.items()
                       if isinstance(v, Mapping) and "scale" in v
                       and "kernel" not in v]
        missing = [k for k in norm_shaped if k not in s]
        if missing:
            raise ValueError(
                f"fold_batchnorm: norm entries {missing} have no matching "
                "batch_stats — pass the SAME model's stats collection")
        norms = norm_shaped
        folded_convs = {}
        for nk in norms:
            ck = _norm_to_conv_name(nk, p)
            kernel = p[ck]["kernel"]
            w, b = _fold_pair(kernel, p[nk]["scale"], p[nk]["bias"],
                              s[nk]["mean"], s[nk]["var"], eps)
            folded_convs[ck] = {"kernel": w, "bias": b}
        for k, v in p.items():
            if k in norms:
                continue  # absorbed
            if k in folded_convs:
                out[k] = folded_convs[k]
            elif isinstance(v, Mapping):
                out[k] = walk(dict(v), dict(s.get(k, {})))
            else:
                out[k] = v
        return out

    return walk(dict(params), dict(batch_stats))
