"""Decoder-only transformer LM with pluggable attention.

Net-new vs the reference (which predates attention entirely, SURVEY.md
§5.7): the long-context workhorse of the rebuild. The attention inner
function is injectable so the SAME module runs

  * dense single-device attention (default, the correctness oracle), or
  * ring attention / Ulysses inside a sequence-sharded ``shard_map``
    (``bluefog_tpu.parallel.cp_apply``), where each device holds S/n tokens.

Positions are explicit arguments so sequence-sharded calls can feed global
token positions to the rotary embedding.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel.context import reference_attention


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding over [B, S, H, D] with positions [S] or [B, S]."""
    d2 = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(0, d2, dtype=jnp.float32) / d2)
    if positions.ndim == 1:
        positions = positions[None]
    ang = positions[..., None].astype(jnp.float32) * freqs  # [B, S, d2]
    sin = jnp.sin(ang)[:, :, None, :]
    cos = jnp.cos(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any
    attn_fn: Callable

    @nn.compact
    def __call__(self, x, positions):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32,
                        use_bias=False)
        h = nn.RMSNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        qkv = dense(3 * d_model, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = q.shape[:2] + (self.num_heads, head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
        a = self.attn_fn(q, k, v)
        a = a.reshape(a.shape[:2] + (d_model,))
        x = x + dense(d_model, name="out")(a)
        h = nn.RMSNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        h = dense(self.d_ff, name="up")(h)
        h = nn.gelu(h)
        x = x + dense(d_model, name="down")(h)
        return x


class TransformerLM(nn.Module):
    """Causal LM. ``attn_fn(q, k, v) -> out`` defaults to dense attention."""

    vocab_size: int
    num_layers: int = 2
    num_heads: int = 4
    d_model: int = 128
    d_ff: int = 512
    dtype: Any = jnp.float32
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, positions=None):
        attn = self.attn_fn or partial(reference_attention, causal=True)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     param_dtype=jnp.float32, name="embed")(tokens)
        for i in range(self.num_layers):
            x = Block(self.num_heads, self.d_ff, self.dtype, attn,
                      name=f"block_{i}")(x, positions)
        x = nn.RMSNorm(dtype=self.dtype, param_dtype=jnp.float32,
                       name="final_norm")(x)
        logits = nn.Dense(self.vocab_size, dtype=self.dtype,
                          param_dtype=jnp.float32, use_bias=False,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)
