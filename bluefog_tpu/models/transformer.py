"""Decoder-only transformer LM with pluggable attention.

Net-new vs the reference (which predates attention entirely, SURVEY.md
§5.7): the long-context workhorse of the rebuild. The attention inner
function is injectable so the SAME module runs

  * dense single-device attention (default, the correctness oracle), or
  * ring attention / Ulysses inside a sequence-sharded ``shard_map``
    (``bluefog_tpu.parallel.cp_apply``), where each device holds S/n tokens.

Positions are explicit arguments so sequence-sharded calls can feed global
token positions to the rotary embedding.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

from ..parallel.context import reference_attention


def apply_rope(x, positions, base: float = 10000.0):
    """Rotary position embedding over [B, S, H, D] with positions [S] or [B, S]."""
    d2 = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(0, d2, dtype=jnp.float32) / d2)
    if positions.ndim == 1:
        positions = positions[None]
    ang = positions[..., None].astype(jnp.float32) * freqs  # [B, S, d2]
    sin = jnp.sin(ang)[:, :, None, :]
    cos = jnp.cos(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention_sublayer(num_heads, dtype, attn_fn, dense, x, positions):
    """Pre-norm attention residual shared by Block and MoEBlock — one
    source of truth for the qkv/rope/attn/out sequence (submodules created
    here attach to the CALLING module's scope with the same auto/explicit
    names both block types had, so param trees are unchanged)."""
    d_model = x.shape[-1]
    head_dim = d_model // num_heads
    h = nn.RMSNorm(dtype=dtype, param_dtype=jnp.float32)(x)
    qkv = dense(3 * d_model, name="qkv")(h)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = q.shape[:2] + (num_heads, head_dim)
    q, k, v = (t.reshape(shape) for t in (q, k, v))
    q = apply_rope(q, positions)
    k = apply_rope(k, positions)
    a = attn_fn(q, k, v)
    a = a.reshape(a.shape[:2] + (d_model,))
    return x + dense(d_model, name="out")(a)


class Block(nn.Module):
    num_heads: int
    d_ff: int
    dtype: Any
    attn_fn: Callable

    @nn.compact
    def __call__(self, x, positions):
        d_model = x.shape[-1]
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32,
                        use_bias=False)
        x = _attention_sublayer(self.num_heads, self.dtype, self.attn_fn,
                                dense, x, positions)
        h = nn.RMSNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        h = dense(self.d_ff, name="up")(h)
        h = nn.gelu(h)
        x = x + dense(d_model, name="down")(h)
        return x


class MoEBlock(nn.Module):
    """Transformer block whose FFN is a top-1 Switch mixture of experts.

    Attention is identical to :class:`Block`; the dense up/gelu/down FFN is
    replaced by :class:`bluefog_tpu.parallel.SwitchFFN`. With
    ``expert_axis`` set the block must run inside a ``shard_map`` over that
    mesh axis (one expert per device, ``ep_lm_loss_fn``); with ``None`` it
    is the dense oracle that runs anywhere.
    """

    num_heads: int
    d_ff: int
    num_experts: int
    dtype: Any
    attn_fn: Callable
    expert_axis: Optional[str] = None
    capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x, positions):
        from ..parallel.expert import SwitchFFN

        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32,
                        use_bias=False)
        x = _attention_sublayer(self.num_heads, self.dtype, self.attn_fn,
                                dense, x, positions)
        h = nn.RMSNorm(dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = x + SwitchFFN(
            num_experts=self.num_experts, d_ff=self.d_ff, dtype=self.dtype,
            expert_axis=self.expert_axis,
            capacity_factor=self.capacity_factor, name="moe")(h)
        return x


class TransformerLM(nn.Module):
    """Causal LM. ``attn_fn(q, k, v) -> out`` defaults to dense attention.

    ``num_experts > 0`` turns every ``moe_every``-th block into a
    :class:`MoEBlock` (Switch MoE FFN) — the sparse-expert LM family the
    dense zoo lacked. ``expert_axis`` selects the sparse expert-parallel
    execution mode (see :class:`MoEBlock`).
    """

    vocab_size: int
    num_layers: int = 2
    num_heads: int = 4
    d_model: int = 128
    d_ff: int = 512
    dtype: Any = jnp.float32
    attn_fn: Optional[Callable] = None
    num_experts: int = 0
    moe_every: int = 2
    expert_axis: Optional[str] = None
    capacity_factor: float = 2.0

    def setup(self):
        # setup (not compact) so ``hidden`` is separately applyable
        # (``model.apply(vars, toks, method="hidden")`` — the chunked-CE
        # training path projects to vocab per sequence chunk instead of
        # materializing [S, V] logits). setattr keeps the original
        # per-index submodule names, so param trees are unchanged.
        attn = self.attn_fn or partial(reference_attention, causal=True)
        setattr(self, "embed", nn.Embed(
            self.vocab_size, self.d_model, dtype=self.dtype,
            param_dtype=jnp.float32))
        for i in range(self.num_layers):
            if self.num_experts and (i + 1) % self.moe_every == 0:
                blk = MoEBlock(self.num_heads, self.d_ff, self.num_experts,
                               self.dtype, attn,
                               expert_axis=self.expert_axis,
                               capacity_factor=self.capacity_factor)
            else:
                blk = Block(self.num_heads, self.d_ff, self.dtype, attn)
            setattr(self, f"block_{i}", blk)
        setattr(self, "final_norm", nn.RMSNorm(
            dtype=self.dtype, param_dtype=jnp.float32))
        setattr(self, "lm_head", nn.Dense(
            self.vocab_size, dtype=self.dtype, param_dtype=jnp.float32,
            use_bias=False))

    def hidden(self, tokens, positions=None):
        """Backbone output [B, S, d_model] BEFORE the vocab projection."""
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        x = self.embed(tokens)
        for i in range(self.num_layers):
            x = getattr(self, f"block_{i}")(x, positions)
        return self.final_norm(x)

    def __call__(self, tokens, positions=None):
        logits = self.lm_head(self.hidden(tokens, positions))
        return logits.astype(jnp.float32)


def MoETransformerLM(vocab_size: int, num_experts: int, **kw):
    """Convenience constructor: a TransformerLM with Switch-MoE FFN blocks
    (Fedus et al. 2021). See :class:`TransformerLM` for the knobs."""
    return TransformerLM(vocab_size=vocab_size, num_experts=num_experts,
                         **kw)
