"""VGG in flax — the reference benchmark's second model family.

The reference's harness loads any torchvision model by name and its docs
exercise ``--model vgg16`` alongside resnet50 (reference:
examples/pytorch_benchmark.py model arg). From-scratch flax implementation
of Simonyan & Zisserman 2014 configurations A/D/E (VGG-11/16/19), with the
batch-norm variant as default — same TPU recipe as the ResNets: bfloat16
compute, float32 params/statistics, NHWC, static shapes.

The torchvision-parity classifier head (two 4096-wide dense layers on the
7x7 feature map) is kept: those matmuls are where VGG's FLOPs live, and
4096 is MXU-lane aligned. torchvision reaches the fixed 7x7 map with an
adaptive average pool; the static-shape analog here average-pools whenever
the post-conv map is a multiple of 7 (224, 448, ... inputs), so those
resolutions share classifier shapes. Other resolutions flatten as-is —
shapes are fixed at init, the XLA contract.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Union

import jax.numpy as jnp
from flax import linen as nn

# torchvision cfgs: ints are conv widths, "M" is 2x2 max-pool.
_CFGS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(nn.Module):
    """Configurable VGG over NHWC inputs.

    With ``batch_norm=True`` (default) apply like the ResNets plus a dropout
    stream: ``model.apply({'params': p, 'batch_stats': s}, x, train=True,
    mutable=['batch_stats'], rngs={'dropout': key})``; with
    ``batch_norm=False`` there is no mutable state and ``train`` only gates
    dropout. ``train=False`` (or ``dropout_rate=0``) needs no rngs.
    """

    cfg: Sequence[Union[int, str]]
    num_classes: int = 1000
    batch_norm: bool = True
    dropout_rate: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        # torchvision's make_layers uses bias=True for every conv even in
        # the batch-norm variant; keep that parameter set so a future
        # vgg_from_torch interop (like resnet_from_torch) maps name-for-name.
        conv = partial(
            nn.Conv, kernel_size=(3, 3), use_bias=True,
            dtype=self.dtype, param_dtype=jnp.float32, padding="SAME",
        )
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
        )
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)

        x = x.astype(self.dtype)
        for i, v in enumerate(self.cfg):
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = conv(v, name=f"conv_{i}")(x)
                if self.batch_norm:
                    x = norm(name=f"bn_{i}")(x)
                x = nn.relu(x)
        # static-shape analog of torchvision's AdaptiveAvgPool2d((7, 7)):
        # inputs whose post-conv map is a multiple of 7 (224 -> 7, 448 -> 14)
        # pool down to the canonical 7x7, sharing classifier shapes.
        h, w = x.shape[1], x.shape[2]
        if (h, w) != (7, 7) and h % 7 == 0 and w % 7 == 0:
            x = nn.avg_pool(x, (h // 7, w // 7), strides=(h // 7, w // 7))
        x = x.reshape((x.shape[0], -1))  # [b, 7*7*512] at 224^2 input
        for j in range(2):
            x = nn.relu(dense(4096, name=f"fc_{j}")(x))
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = dense(self.num_classes, name="head")(x)
        return x.astype(jnp.float32)


VGG11 = partial(VGG, cfg=_CFGS[11])
VGG16 = partial(VGG, cfg=_CFGS[16])
VGG19 = partial(VGG, cfg=_CFGS[19])
