"""ResNet v1.5 in flax — the flagship benchmark model.

The reference's headline numbers are ResNet-50 throughput/scaling under its
benchmark harness (reference: examples/pytorch_benchmark.py, which loads
``torchvision.models.resnet50``; docs/performance.rst:13-24). This is a
from-scratch flax implementation of the same architecture (He et al. 2015,
v1.5 stride placement: stride-2 on the 3x3 conv inside bottlenecks, the
variant torchvision implements), tuned for TPU:

  * compute dtype bfloat16, parameters and batch-norm statistics float32 —
    the standard MXU recipe (matmuls/convs run bf16 on the systolic array,
    accumulation in f32).
  * NHWC layout (XLA:TPU's native conv layout).
  * all shapes static; no Python branching on data.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (ResNet-50/101)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity
        # (goyal et al. large-batch recipe; torchvision does the same).
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """Two 3x3 convs (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet over NHWC inputs.

    Apply with ``model.apply({'params': p, 'batch_stats': s}, x, train=True,
    mutable=['batch_stats'])`` during training.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem: str = "conv"  # "conv" (7x7/2, torchvision parity) | "space_to_depth"
    # Inference-only variant consuming ``models.fold_batchnorm`` output:
    # every BatchNorm collapses into the preceding conv's weights + a bias,
    # so the apply carries no batch_stats collection at all. Training with
    # fold_bn=True is meaningless (there is no norm to update) and rejected.
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.fold_bn and train:
            raise ValueError("fold_bn=True is an inference-only variant; "
                             "apply with train=False")

        def conv(features, kernel_size, strides=(1, 1), name=None):
            # explicit ((k-1)//2, k//2) padding: identical to SAME at
            # stride 1 for every kernel, and — symmetric for the odd
            # kernels the architecture uses — matches torch's
            # Conv2d(padding=k//2) at stride 2 too (where SAME pads
            # asymmetrically). Keeps forwards numerically equal to
            # torchvision weights loaded via utils/torch_interop.py.
            k = kernel_size[0]
            return nn.Conv(
                features, kernel_size, strides, use_bias=self.fold_bn,
                dtype=self.dtype, param_dtype=jnp.float32,
                padding=(((k - 1) // 2, k // 2), ((k - 1) // 2, k // 2)),
                name=name)
        if self.fold_bn:
            def norm(name=None, **kw):  # noqa: ARG001 — absorbed into conv
                return lambda x: x
        else:
            norm = partial(
                nn.BatchNorm, use_running_average=not train, momentum=0.9,
                epsilon=1e-5, dtype=self.dtype, param_dtype=jnp.float32,
            )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            # MLPerf-style stem: 2x2 space-to-depth packs the 3-channel
            # input into 12 channels at half resolution, turning the padded
            # stride-2 7x7 conv (3 input channels badly under-fill the
            # MXU's 128-lane contraction) into a dense stride-1 4x4 conv at
            # the same output shape/receptive field class. Compute-
            # equivalent stand-in for conv_init, not weight-compatible.
            b, h, w, c = x.shape
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), name="conv_init_s2d")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2),
                        padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv, norm=norm, act=act, strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=jnp.float32,
            name="head",
        )(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
