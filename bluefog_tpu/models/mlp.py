"""Small models for examples and tests.

Counterparts of the reference's example networks: the MNIST CNN defined
inline in examples/pytorch_mnist.py (two convs + two dense) and the linear /
logistic-regression models of examples/pytorch_least_square.py. Small enough
to train on a simulated CPU mesh in tests.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class MLP(nn.Module):
    """Plain MLP; default geometry suits flattened-MNIST consensus tests."""

    features: Sequence[int] = (128, 128, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype, param_dtype=jnp.float32)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x.astype(jnp.float32)


class LeNet5(nn.Module):
    """Conv net of the reference MNIST example (examples/pytorch_mnist.py)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
