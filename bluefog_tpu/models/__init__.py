"""Model zoo for bluefog_tpu benchmarks, examples, and tests.

The reference framework has no model code of its own — its examples pull
torchvision models (reference: examples/pytorch_benchmark.py uses
``torchvision.models.resnet50``, examples/pytorch_mnist.py defines a small
CNN). A standalone TPU framework cannot lean on torchvision, so the
equivalents live here as flax modules designed for the MXU: bfloat16 compute
with float32 parameters/batch-stats, channel counts that are multiples of
128 where the architecture allows, and no data-dependent Python control flow.
"""

from .mlp import MLP, LeNet5
from .fold import fold_batchnorm
from .resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101
from .transformer import MoEBlock, MoETransformerLM, TransformerLM, apply_rope
from .vgg import VGG, VGG11, VGG16, VGG19

__all__ = [
    "MLP",
    "LeNet5",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "fold_batchnorm",
    "MoEBlock",
    "MoETransformerLM",
    "TransformerLM",
    "apply_rope",
    "VGG",
    "VGG11",
    "VGG16",
    "VGG19",
]
