"""Versioned, immutable model snapshots over the control-plane KV wire.

Wire format (docs/serving.md). A snapshot is the model's leaves raveled
to ONE float32 vector, cut into ``S`` contiguous segments ("snapshot
shards" — independent pull units that hash across the control-plane
shard servers), each published under::

    bf.serve.snap.<ver>.<shard>

as a 24-byte header + payload::

    <IBBHQQ  magic, codec_id, flags, shard, ver, element_count

followed by either raw little-endian float32 bytes (codec 0) or a
self-describing r15 codec payload (``ops/codec.py`` — int8/fp8 bounded
-error absolute state; top-k is never used for state and
:func:`~bluefog_tpu.ops.codec.state_codec_for` substitutes int8).

**Version fence.** Snapshot keys are immutable once written: a version's
bytes never change (they are only ever GC'd). The monotone scalar
``bf.serve.ver`` (``put_max``) is written ONLY after every shard of that
version landed, so a reader that pulls the fence value and then the
fence's keys can never observe a torn snapshot — a publisher killed
mid-publish leaves the fence at the last complete version (the r16 WAL'd
``kPutBytes``/``kPutMax`` path makes both survive a shard failover).
Old versions are GC'd (overwritten with empty bytes) once more than the
keep window (``BLUEFOG_SERVE_KEEP``) of newer versions committed;
``bf.serve.gc_floor`` (monotone) names the oldest retained version so a
reader can tell "GC'd" from "never existed".
"""

from __future__ import annotations

import json
import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops import codec as _codec
from ..runtime import flight as _flight
from ..runtime.config import knob_env
from ..runtime.logging import logger

SNAP_KEY_FMT = "bf.serve.snap.{ver}.{shard}"
VER_KEY = "bf.serve.ver"
META_KEY = "bf.serve.meta"
PUB_TS_KEY = "bf.serve.pub_ts"
PUB_STEP_KEY = "bf.serve.pub_step"
GC_FLOOR_KEY = "bf.serve.gc_floor"
CLIENTS_KEY = "bf.serve.clients"
CLIENT_HB_FMT = "bf.serve.client.{cid}"
LINEAGE_KEY_FMT = "bf.serve.lineage.{ver}"

_MAGIC = 0x56734642  # "BFsV" little-endian
_HDR = struct.Struct("<IBBHQQ")

# header flags bit: a lineage record rides this version's KV sidecar
# (decode ignores flags, so pre-tracing readers interoperate unchanged)
FLAG_LINEAGE = 0x1


class SnapshotGone(RuntimeError):
    """A shard of the requested version is no longer (or not yet) on the
    wire — the version was GC'd beneath the reader, who should re-read
    the fence and retry at the current version."""


def _put_float(cl, key: str, value: float) -> None:
    cl.put(key, struct.unpack("<q", struct.pack("<d", float(value)))[0])


def _get_float(cl, key: str) -> float:
    return struct.unpack("<d", struct.pack("<q", int(cl.get(key))))[0]


class SnapshotMeta:
    """Shape/dtype/striping sidecar (``bf.serve.meta``, JSON).

    Published once (it only depends on the model structure and shard
    count, never on the version), so a fetch is ``1 + S`` reads. The
    float32 flat layout is the concatenation of every leaf raveled in
    tree-flatten order; ``boundaries[s]:boundaries[s+1]`` is shard ``s``'s
    element range.
    """

    def __init__(self, leaves: Sequence[Tuple[Tuple[int, ...], str]],
                 shards: int) -> None:
        self.leaves = [(tuple(int(d) for d in shp), str(dt))
                       for shp, dt in leaves]
        self.sizes = [int(np.prod(shp, dtype=np.int64)) if shp else 1
                      for shp, _ in self.leaves]
        self.total = int(sum(self.sizes))
        self.shards = max(1, min(int(shards), max(1, self.total)))
        self.boundaries = [self.total * s // self.shards
                           for s in range(self.shards + 1)]

    @classmethod
    def for_arrays(cls, arrays: Sequence[np.ndarray],
                   shards: int) -> "SnapshotMeta":
        return cls([(tuple(a.shape), np.dtype(a.dtype).name)
                    for a in arrays], shards)

    def to_json(self) -> bytes:
        return json.dumps({
            "fmt": 1,
            "shards": self.shards,
            "leaves": [[list(shp), dt] for shp, dt in self.leaves],
        }, sort_keys=True).encode()

    @classmethod
    def from_json(cls, blob) -> "SnapshotMeta":
        doc = json.loads(bytes(blob).decode())
        if doc.get("fmt") != 1:
            raise ValueError(
                f"snapshot meta format {doc.get('fmt')!r} is newer than "
                "this build understands")
        return cls([(tuple(shp), dt) for shp, dt in doc["leaves"]],
                   doc["shards"])

    def segment(self, shard: int) -> Tuple[int, int]:
        return self.boundaries[shard], self.boundaries[shard + 1]

    def split(self, flat: np.ndarray) -> List[np.ndarray]:
        """Flat float32 vector -> leaves in their declared shapes/dtypes
        (a bf16 leaf comes back float32 — numpy has no bf16; the serving
        docs pin this as the fetch-path contract)."""
        out: List[np.ndarray] = []
        off = 0
        for (shp, dt), n in zip(self.leaves, self.sizes):
            seg = flat[off:off + n]
            off += n
            try:
                arr = seg.astype(np.dtype(dt), copy=False)
            except TypeError:
                arr = seg  # non-numpy dtype name (bfloat16): keep f32
            out.append(arr.reshape(shp))
        return out


def serve_shard_count() -> int:
    """Snapshot pull-unit count: ``BLUEFOG_SERVE_SHARDS``, falling back
    to the r17 window shard factor so a sharded trainer's serving plane
    stripes the same way its gossip wire does."""
    s = int(knob_env("BLUEFOG_SERVE_SHARDS") or 0)
    if s <= 0:
        s = int(knob_env("BLUEFOG_WIN_SHARD") or 1)
    return max(1, s)


def resolve_serve_codec(train_codec=None):
    """The snapshot codec: ``BLUEFOG_SERVE_CODEC`` when set (``none``
    forces raw), else the trainer's wire codec routed through
    ``state_codec_for`` (bounded-error dense state only — top-k falls
    back to int8, exactly like published window rows)."""
    spec = knob_env("BLUEFOG_SERVE_CODEC")
    if spec:
        return _codec.state_codec_for(_codec.resolve(spec))
    return _codec.state_codec_for(train_codec)


def flatten_leaves(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Leaves -> one contiguous float32 vector (the snapshot layout)."""
    if not arrays:
        return np.zeros(0, np.float32)
    return np.concatenate(
        [np.asarray(a).reshape(-1).astype(np.float32, copy=False)
         for a in arrays])


def encode_shard(flat: np.ndarray, meta: SnapshotMeta, shard: int,
                 ver: int, codec=None, flags: int = 0) -> bytes:
    lo, hi = meta.segment(shard)
    seg = np.ascontiguousarray(flat[lo:hi], np.float32)
    if codec is None:
        payload = seg.view(np.uint8)
        cid = _codec.CODEC_NONE
    else:
        payload = codec.encode(seg)
        cid = codec.cid
    out = np.empty(_HDR.size + payload.nbytes, np.uint8)
    out[:_HDR.size] = np.frombuffer(
        _HDR.pack(_MAGIC, cid, flags & 0xFF, shard, ver, hi - lo), np.uint8)
    out[_HDR.size:] = payload.reshape(-1)
    return out.tobytes()


def decode_shard(blob, meta: SnapshotMeta, shard: int,
                 ver: Optional[int] = None) -> Tuple[np.ndarray, int]:
    """One published shard payload -> (float32 segment, its version).

    Empty/GC'd slots raise :class:`SnapshotGone`; anything structurally
    wrong (bad magic, wrong shard, wrong element count) raises
    ValueError — immutable keys make that corruption, not a race.
    """
    if blob is None or len(blob) == 0:
        raise SnapshotGone(
            f"snapshot shard {shard} of version {ver} is not on the wire "
            "(GC'd past the keep window, or never published)")
    raw = np.frombuffer(blob, np.uint8) if not isinstance(
        blob, np.ndarray) else blob
    if raw.size < _HDR.size:
        raise ValueError(
            f"snapshot shard {shard}: {raw.size}-byte payload is shorter "
            "than the header")
    magic, cid, _flags, got_shard, got_ver, count = _HDR.unpack_from(
        raw[:_HDR.size].tobytes())
    if magic != _MAGIC:
        raise ValueError(
            f"snapshot shard {shard}: bad magic {magic:#x} (key collision "
            "with a non-serving bytes slot?)")
    if got_shard != shard:
        raise ValueError(
            f"snapshot shard index mismatch: key says {shard}, header "
            f"says {got_shard}")
    if ver is not None and got_ver != ver:
        raise ValueError(
            f"snapshot shard {shard}: header version {got_ver} under a "
            f"version-{ver} key — immutable-key contract violated")
    lo, hi = meta.segment(shard)
    if count != hi - lo:
        raise ValueError(
            f"snapshot shard {shard}: {count} elements on the wire, meta "
            f"says {hi - lo} — stale bf.serve.meta?")
    payload = raw[_HDR.size:]
    if cid == _codec.CODEC_NONE:
        if payload.nbytes != 4 * count:
            raise ValueError(
                f"snapshot shard {shard}: raw payload is {payload.nbytes} "
                f"bytes for {count} float32 elements")
        seg = payload.view(np.float32).copy()
    else:
        seg = _codec.by_id(cid).decode(payload, np.float32, int(count))
    return seg, int(got_ver)


def current_version(cl) -> int:
    """The committed snapshot version (0 = nothing published yet)."""
    return max(0, int(cl.get(VER_KEY)))


def trace_flow_id(key: str) -> int:
    """Stable 63-bit flow id for a snapshot shard key. The publisher's
    FLOW_S and the puller's FLOW_F derive the same id from the key alone —
    that shared id is what binds the two ring records into one chrome flow
    arrow when per-process dumps are merged."""
    h = 0xCBF29CE484222325
    for ch in key.encode():
        h = ((h ^ ch) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h & 0x7FFFFFFFFFFFFFFF


def read_lineage(cl, ver: int) -> Optional[dict]:
    """The publisher-stamped lineage record for a committed version:
    ``{"fmt", "ver", "step", "rank", "codec", "wall_us"}`` — which
    training step (on which rank, through which codec, at what wall
    clock) produced the bytes that answered a request. None when absent
    (tracing off at the publisher, pre-tracing publisher, or GC'd)."""
    try:
        blob = cl.get_bytes(LINEAGE_KEY_FMT.format(ver=int(ver)))
    except (OSError, RuntimeError):
        return None
    if not blob:
        return None
    try:
        doc = json.loads(bytes(blob).decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return doc if doc.get("fmt") == 1 else None


def fetch_meta(cl) -> Optional[SnapshotMeta]:
    try:
        blob = cl.get_bytes(META_KEY)
    except (OSError, RuntimeError):
        return None
    if not blob:
        return None
    return SnapshotMeta.from_json(blob)


def snap_keys(meta: SnapshotMeta, ver: int) -> List[str]:
    return [SNAP_KEY_FMT.format(ver=ver, shard=s)
            for s in range(meta.shards)]


def fetch_snapshot(cl, meta: Optional[SnapshotMeta] = None,
                   ver: Optional[int] = None, pull=None, retries: int = 4):
    """Pull one complete snapshot.

    Returns ``(leaves, version, wire_bytes)`` or ``None`` when nothing
    is committed yet. ``pull(keys) -> [blob]`` injects a transport (the
    serve client passes its parallel per-endpoint puller; the default is
    the attached client's pipelined ``get_bytes_many``). A version GC'd
    mid-pull re-reads the fence and retries at the current version —
    with a positive keep window that terminates unless the reader lags
    the publisher by the whole window every attempt.
    """
    if meta is None:
        meta = fetch_meta(cl)
        if meta is None:
            return None
    pinned = ver is not None
    last: Optional[Exception] = None
    for _ in range(max(1, retries)):
        v = ver if pinned else current_version(cl)
        if not v:
            return None
        keys = snap_keys(meta, v)
        blobs = pull(keys) if pull is not None else cl.get_bytes_many(keys)
        try:
            segs = [decode_shard(b, meta, s, v)[0]
                    for s, b in enumerate(blobs)]
        except SnapshotGone as exc:
            if pinned:
                raise
            last = exc
            continue
        flat = segs[0] if len(segs) == 1 else np.concatenate(segs)
        wire = sum(len(b) for b in blobs if b is not None)
        return meta.split(flat), v, int(wire)
    raise SnapshotGone(
        f"snapshot fetch lost the GC race {retries} times in a row "
        f"(last: {last}); raise BLUEFOG_SERVE_KEEP on the publisher")


class SnapshotPublisher:
    """Training-side publisher: encode, land every shard, THEN move the
    fence; GC versions beyond the keep window. One publisher per job
    (the optimizer hook runs it on controller 0 only) — the fence is
    monotone ``put_max``, so even a misconfigured second publisher can
    only ever advance it to a version whose shards are fully landed."""

    def __init__(self, cl, shards: Optional[int] = None, codec=None,
                 keep: Optional[int] = None) -> None:
        self._cl = cl
        self._shards = shards if shards and shards > 0 \
            else serve_shard_count()
        self._codec = codec
        keep = int(knob_env("BLUEFOG_SERVE_KEEP")) if keep is None \
            else int(keep)
        self._keep = max(1, keep)
        self._meta: Optional[SnapshotMeta] = None
        self._committed: List[int] = []
        self._last_ver = 0
        # request-path tracing (BLUEFOG_TRACE_SERVE): when on, every
        # publish stamps a lineage record and records publish spans +
        # per-shard flow starts; when off, nothing new touches the wire
        # or the ring (the zero-touch pin).
        self._trace = bool(knob_env("BLUEFOG_TRACE_SERVE"))
        # test-only: sleep between shard writes so a chaos harness can
        # SIGKILL this process deterministically mid-publish
        self._inter_shard_sleep = 0.0

    @property
    def meta(self) -> Optional[SnapshotMeta]:
        return self._meta

    def publish(self, arrays: Sequence[np.ndarray], ver: int,
                step: Optional[int] = None) -> Dict[str, float]:
        """Publish ``arrays`` as version ``ver`` (must be > the last
        version this publisher committed). Returns wire accounting:
        ``raw_bytes``, ``wire_bytes``, ``seconds``, ``version``."""
        ver = int(ver)
        if ver <= self._last_ver:
            raise ValueError(
                f"snapshot versions are monotone: {ver} after "
                f"{self._last_ver}")
        t0 = time.perf_counter()
        if self._meta is None:
            self._meta = SnapshotMeta.for_arrays(
                [np.asarray(a) for a in arrays], self._shards)
            self._cl.put_bytes(META_KEY, self._meta.to_json())
        flat = flatten_leaves(arrays)
        if flat.size != self._meta.total:
            raise ValueError(
                f"snapshot publish: {flat.size} elements, meta declares "
                f"{self._meta.total} — model structure changed under a "
                "live publisher")
        keys = snap_keys(self._meta, ver)
        flags = FLAG_LINEAGE if self._trace else 0
        blobs = [encode_shard(flat, self._meta, s, ver, self._codec,
                              flags=flags)
                 for s in range(self._meta.shards)]
        rec = _flight.recorder() if self._trace else None
        if rec is not None:
            rec.begin("serve.publish", float(flat.nbytes), ver)
        if self._inter_shard_sleep > 0:
            for k, b in zip(keys, blobs):
                self._cl.put_bytes(k, b)
                if rec is not None:
                    rec.rec(_flight.FLOW_S, rec.intern("serve.snap"),
                            float(len(b)), trace_flow_id(k))
                time.sleep(self._inter_shard_sleep)
        else:
            self._cl.put_bytes_many(keys, blobs)
            if rec is not None:
                for k, b in zip(keys, blobs):
                    rec.rec(_flight.FLOW_S, rec.intern("serve.snap"),
                            float(len(b)), trace_flow_id(k))
        if self._trace:
            # lineage lands BEFORE the fence so a reader that saw the
            # fence move can always resolve the producing step
            lineage = {"fmt": 1, "ver": ver,
                       "step": int(step) if step is not None else -1,
                       "rank": self._lineage_rank(),
                       "codec": (self._codec.cid if self._codec
                                 else _codec.CODEC_NONE),
                       "wall_us": time.time_ns() // 1000}
            self._cl.put_bytes(LINEAGE_KEY_FMT.format(ver=ver),
                               json.dumps(lineage, sort_keys=True).encode())
        # every shard is on the wire: move the fence, then the gauges
        self._cl.put_max(VER_KEY, ver)
        if rec is not None:
            rec.end("serve.publish", float(flat.nbytes), ver)
        self._last_ver = ver
        _put_float(self._cl, PUB_TS_KEY, time.time())
        if step is not None:
            self._cl.put(PUB_STEP_KEY, int(step))
        self._committed.append(ver)
        self._gc()
        return {"version": ver, "raw_bytes": float(flat.nbytes),
                "wire_bytes": float(sum(len(b) for b in blobs)),
                "seconds": time.perf_counter() - t0}

    def _lineage_rank(self) -> int:
        try:
            from ..runtime import metrics as _metrics

            return int(_metrics._process_index())
        except Exception:  # noqa: BLE001 — lineage is telemetry
            return 0

    def _gc(self) -> None:
        """Overwrite versions beyond the keep window with empty bytes
        (the KV has no delete op; an empty slot frees the payload and
        reads as absent). The floor moves BEFORE the bytes vanish so a
        reader can always classify a miss. Lineage sidecars are GC'd with
        their version (only when tracing stamped them — an untraced run
        never creates, nor clears, the keys)."""
        while len(self._committed) > self._keep:
            old = self._committed.pop(0)
            floor = self._committed[0]
            gc_keys = snap_keys(self._meta, old)
            if self._trace:
                gc_keys = gc_keys + [LINEAGE_KEY_FMT.format(ver=old)]
            try:
                self._cl.put_max(GC_FLOOR_KEY, floor)
                self._cl.put_bytes_many(gc_keys, [b""] * len(gc_keys))
            except (OSError, RuntimeError) as exc:
                logger.warning(
                    "serve publisher: GC of snapshot version %d failed "
                    "(%s); the slot stays until the next publish", old,
                    exc)
                return


def claim_client_slot(cl) -> int:
    """Register a serve client: reuse the first EXPIRED heartbeat slot
    (no beat for longer than ``BLUEFOG_SERVE_CLIENT_TTL_S``, or zeroed by
    a clean close) before growing ``bf.serve.clients`` — so the
    ``bf.serve.client.<cid>`` key set, the ``--status``/``--top`` client
    tables fed by it, and the admission gate's client count stay bounded
    by the PEAK concurrent client count instead of growing forever.

    Two clients registering at once can double-claim a slot; client
    identity is observability, not correctness (the same trade the
    heartbeat itself makes), and the loser's next beat simply keeps the
    shared slot warm. Returns -1 when the KV is unreachable."""
    ttl = float(knob_env("BLUEFOG_SERVE_CLIENT_TTL_S"))
    now = time.time()
    try:
        total = max(0, int(cl.get(CLIENTS_KEY)))
        for cid in range(min(total, 256)):
            ts = _get_float(cl, CLIENT_HB_FMT.format(cid=cid))
            if ts <= 0 or (ttl > 0 and now - ts > ttl):
                _put_float(cl, CLIENT_HB_FMT.format(cid=cid), now)
                return cid
        cid = int(cl.fetch_add(CLIENTS_KEY, 1))
        _put_float(cl, CLIENT_HB_FMT.format(cid=cid), now)
        return cid
    except (OSError, RuntimeError):
        return -1


def release_client_slot(cl, cid: int) -> None:
    """Zero the heartbeat on clean close so the slot reads as free
    immediately (a crashed client's slot frees via the TTL instead)."""
    if cid < 0:
        return
    try:
        _put_float(cl, CLIENT_HB_FMT.format(cid=cid), 0.0)
    except (OSError, RuntimeError):
        pass


def read_serve_status(cl, hb_window_s: Optional[float] = None
                      ) -> Optional[dict]:
    """The serving-plane status row set (``bfrun --status``): committed
    version, publish lag, publisher step, GC floor, and attached-client
    counts. None when no serving plane ever published here."""
    try:
        ver = current_version(cl)
        meta_len = cl.bytes_len(META_KEY)
    except (OSError, RuntimeError):
        return None
    if ver <= 0 and meta_len <= 0:
        return None
    pub_ts = _get_float(cl, PUB_TS_KEY)
    lag = max(0.0, time.time() - pub_ts) if pub_ts > 0 else None
    if hb_window_s is None:
        hb_window_s = 6.0 * float(knob_env("BLUEFOG_SERVE_POLL_S"))
    total = max(0, int(cl.get(CLIENTS_KEY)))
    live = 0
    now = time.time()
    for cid in range(min(total, 256)):
        ts = _get_float(cl, CLIENT_HB_FMT.format(cid=cid))
        if ts > 0 and now - ts <= hb_window_s:
            live += 1
    return {
        "version": ver,
        "publish_lag_s": lag,
        "pub_step": max(0, int(cl.get(PUB_STEP_KEY))),
        "gc_floor": max(0, int(cl.get(GC_FLOOR_KEY))),
        "shards": (fetch_meta(cl).shards if meta_len > 0 else 0),
        "clients_total": total,
        "clients_live": live,
    }


def live_client_ids(cl, hb_window_s: Optional[float] = None) -> List[int]:
    """Client ids with a live heartbeat — the ``--top``/``--status``
    scan over ``bf.serve.client.<id>`` (bounded by the same 256-slot
    window as :func:`read_serve_status`)."""
    if hb_window_s is None:
        hb_window_s = 6.0 * float(knob_env("BLUEFOG_SERVE_POLL_S"))
    try:
        total = max(0, int(cl.get(CLIENTS_KEY)))
    except (OSError, RuntimeError):
        return []
    out: List[int] = []
    now = time.time()
    for cid in range(min(total, 256)):
        try:
            ts = _get_float(cl, CLIENT_HB_FMT.format(cid=cid))
        except (OSError, RuntimeError):
            continue
        if ts > 0 and now - ts <= hb_window_s:
            out.append(cid)
    return out
