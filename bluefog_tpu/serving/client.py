"""Read-only inference client over the snapshot plane.

A :class:`ServeClient` attaches to the control plane the way ``bfrun
--status`` does — a raw client, no mesh join, no jax anywhere on the
path — and runs three concerns on top of it:

* **Puller.** A poller thread watches the ``bf.serve.ver`` fence and, on
  a bump, pulls the new snapshot's shards IN PARALLEL: keys are grouped
  by the router's FNV placement, each group is fetched on a dedicated
  per-endpoint client (its own striped-stream pool), so aggregate pull
  bandwidth scales with the control-plane shard count instead of being
  serialized through one socket. The swap is atomic under a lock — a
  request is always served by exactly one complete version.

* **Batcher.** ``submit()`` enqueues a single example and blocks on a
  future; a batcher thread drains the queue into stacked batches (max
  ``BLUEFOG_SERVE_BATCH``, linger ``BLUEFOG_SERVE_BATCH_WAIT_MS``) and
  runs the user's ``model_fn(params, batch)`` once per batch.

* **Admission gate.** Before enqueueing, ``submit()`` consults the r18
  telemetry the trainer is already publishing — queue depth, control
  -plane mailbox pressure, publish lag, live alert blobs — and resolves
  to ``accept`` / ``queue`` (admitted, counted as degraded) / ``shed``
  (:class:`RequestShed`). Serving load can therefore never push the
  control plane into the overload regimes the training side alarms on.
"""

from __future__ import annotations

import itertools
import json
import os
import queue as _queue
import threading
import time
import zlib
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime import flight as _flight
from ..runtime import metrics as _metrics
from ..runtime.config import knob_env
from ..runtime.logging import logger
from ..runtime.router import _fnv64
from . import snapshot as _snap

# Sliding window for the tick-time latency/staleness percentile gauges
# (slotted numpy ring — the per-request store is two array writes).
_PCT_RING = 512

# admission verdict codes carried in the serve.admit span-end `a` column
_ADMIT_CODE = {"accept": 0.0, "queue": 1.0, "shed": 2.0}

# names the tracer pre-interns at attach so the per-request hot path is
# pure rec() calls (no dict hashing beyond one lookup per span edge)
_TRACE_NAMES = ("serve.req", "serve.admit", "serve.queue", "serve.linger",
                "serve.decode", "serve.pull", "serve.pull.ep",
                "serve.failover", "serve.snap")


class RequestShed(RuntimeError):
    """The admission gate refused this request (overload protection).

    Callers should back off and retry later; ``gate`` carries the input
    that tripped (``queue_full`` / ``mailbox`` / ``not_ready``)."""

    def __init__(self, message: str, gate: str = "") -> None:
        self.gate = gate
        super().__init__(message)


def _endpoint_for(key: str, n: int) -> int:
    return _fnv64(key) % n


class ServeClient:
    """Versioned-snapshot puller + batched read-only inference server.

    ``model_fn(params, batch) -> outputs`` runs on stacked numpy batches
    (``params`` is the snapshot's leaf list). Without a ``model_fn`` the
    client still pulls and hot-swaps — ``params()``/``version()`` expose
    the freshest complete snapshot for callers doing their own compute.
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 model_fn: Optional[Callable] = None, *,
                 secret: str = "", streams: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 register: bool = True, start: bool = True) -> None:
        from ..runtime.native import ControlPlaneClient
        from ..runtime.router import ShardRouter

        if not endpoints:
            raise ValueError("ServeClient needs at least one control-plane "
                             "endpoint")
        self._endpoints = [(str(h), int(p)) for h, p in endpoints]
        self._secret = secret
        self._streams = streams
        self._model_fn = model_fn
        self._poll_s = float(knob_env("BLUEFOG_SERVE_POLL_S")) \
            if poll_s is None else float(poll_s)
        # scalar/meta/telemetry path: the same lenient attach --status uses
        if len(self._endpoints) == 1:
            host, port = self._endpoints[0]
            self._cl = ControlPlaneClient(host, port, 0, secret=secret,
                                          streams=1)
        else:
            self._cl = ShardRouter(self._endpoints, 0, secret=secret,
                                   streams=1, lenient=True)
        # bulk path: dedicated per-endpoint clients, dialed lazily so a
        # shard that is down between publishes never blocks attach
        self._bulk: Dict[int, ControlPlaneClient] = {}
        self._bulk_mu = threading.Lock()
        self._pace_mbps = 0.0  # bench/test hook, see pull_blobs()

        self._mu = threading.Lock()          # guards the swap state below
        self._params: Optional[List[np.ndarray]] = None
        self._version = 0
        self._meta: Optional[_snap.SnapshotMeta] = None
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._health: dict = {}
        self._stats = {"swaps": 0, "pulls": 0, "pull_failures": 0,
                       "wire_bytes": 0, "pull_mbps": 0.0,
                       "accepted": 0, "queued": 0, "shed": 0,
                       "requests": 0, "batches": 0}

        # -- request-path tracing + SLO recording (docs/slo.md) -----------
        # Both are opt-in; with the knobs unset NOTHING below records,
        # publishes, or changes the wire (the zero-touch pin).
        from ..runtime.timeseries import parse_slos

        self._trace = bool(knob_env("BLUEFOG_TRACE_SERVE"))
        self._slos = parse_slos(knob_env("BLUEFOG_SLO"))
        self._fence_ver = 0      # latest fence the poller saw (staleness)
        self._failover_open = False
        self._rec = None
        self._nid: Dict[str, int] = {}
        if self._trace:
            r = _flight.recorder()
            self._rec = r
            self._nid = {n: r.intern(n) for n in _TRACE_NAMES}
            # 63-bit trace ids: random high bits per client, low bits a
            # GIL-atomic counter — collision-free enough for a merge
            self._tid_base = (int.from_bytes(os.urandom(6), "little")
                              << 16) & 0x7FFFFFFFFFFFFFFF
            self._tid_iter = itertools.count(1)
            self._m_traced = _metrics.counter("trace.requests")
        self._ts = None
        if self._slos:
            from ..runtime.timeseries import TimeSeriesStore

            self._ts = TimeSeriesStore()
            self._m_req = _metrics.counter("slo.requests")
            self._m_shed = _metrics.counter("slo.shed")
            self._m_lat_h = _metrics.histogram(
                "slo.request_us",
                bounds=(100, 250, 500, 1000, 2500, 5000, 10000, 25000,
                        50000, 100000, 250000, 1000000))
            self._m_stal_h = _metrics.histogram(
                "slo.staleness_ver", bounds=(0, 1, 2, 3, 5, 8, 13, 21, 34))
            self._m_breach = {o.name: _metrics.counter("slo.breach."
                                                       + o.name)
                              for o in self._slos}
            self._lat_ring = np.zeros(_PCT_RING, np.float64)
            self._lat_n = 0
            self._stal_ring = np.zeros(_PCT_RING, np.float64)
            self._stal_n = 0

        qmax = int(knob_env("BLUEFOG_SERVE_QUEUE_MAX"))
        soft = int(knob_env("BLUEFOG_SERVE_QUEUE_SOFT")) or max(1, qmax // 2)
        self._qmax, self._qsoft = qmax, min(soft, qmax)
        self._stale_s = float(knob_env("BLUEFOG_SERVE_STALE_S"))
        self._mailbox_cap: Optional[int] = None
        self._batch_max = max(1, int(knob_env("BLUEFOG_SERVE_BATCH")))
        self._linger_s = max(
            0.0, float(knob_env("BLUEFOG_SERVE_BATCH_WAIT_MS")) / 1e3)
        self._q: "_queue.Queue" = _queue.Queue(maxsize=qmax)

        self._cid = -1
        if register:
            # reuses expired heartbeat slots so bf.serve.client.<cid>
            # keys stay bounded by the peak concurrent client count
            self._cid = _snap.claim_client_slot(self._cl)

        self._threads: List[threading.Thread] = []
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        t = threading.Thread(target=self._poll_loop,
                             name="bf-serve-poll", daemon=True)
        t.start()
        self._threads.append(t)
        if self._model_fn is not None:
            t = threading.Thread(target=self._batch_loop,
                                 name="bf-serve-batch", daemon=True)
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        while True:  # fail anything still parked in the queue
            try:
                item = self._q.get_nowait()
            except _queue.Empty:
                break
            fut = item[1]
            if not fut.done():
                fut.set_exception(RequestShed("serve client closed",
                                              gate="closed"))
        _snap.release_client_slot(self._cl, self._cid)
        with self._bulk_mu:
            for cl in self._bulk.values():
                try:
                    cl.close()
                except (OSError, RuntimeError):
                    pass
            self._bulk.clear()
        try:
            self._cl.close()
        except (OSError, RuntimeError):
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- snapshot access ---------------------------------------------------

    def params(self) -> Optional[List[np.ndarray]]:
        with self._mu:
            return self._params

    def version(self) -> int:
        with self._mu:
            return self._version

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the first complete snapshot is swapped in."""
        return self._ready.wait(timeout)

    def refresh(self) -> int:
        """Synchronous poll: pull and swap if the fence moved. Returns the
        serving version after the check."""
        self._maybe_pull()
        return self.version()

    # -- parallel bulk puller ---------------------------------------------

    def _bulk_client(self, idx: int):
        from ..runtime.native import ControlPlaneClient

        with self._bulk_mu:
            cl = self._bulk.get(idx)
            if cl is None:
                # a shard that died and rejoined on a NEW port re-points
                # the router's endpoint table (bf.cp.shard_addr adoption);
                # bulk re-dials must follow it, not the attach-time copy
                eps = self._cl.endpoints \
                    if hasattr(self._cl, "endpoints") else self._endpoints
                host, port = eps[idx]
                cl = ControlPlaneClient(host, port, 0, secret=self._secret,
                                        streams=self._streams)
                self._bulk[idx] = cl
            return cl

    def _drop_bulk_client(self, idx: int) -> None:
        with self._bulk_mu:
            cl = self._bulk.pop(idx, None)
        if cl is not None:
            try:
                cl.close()
            except (OSError, RuntimeError):
                pass

    def pull_blobs(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """Fetch ``keys`` grouped by FNV placement, one thread + one
        dedicated striped client per control-plane endpoint — the
        fan-out that makes pull bandwidth scale with shard count."""
        n = len(self._endpoints)
        groups: Dict[int, List[int]] = {}
        for pos, key in enumerate(keys):
            groups.setdefault(_endpoint_for(key, n), []).append(pos)
        out: List[Optional[bytes]] = [None] * len(keys)
        errs: List[str] = []

        def pull_group(idx: int, positions: List[int]) -> None:
            t0 = time.perf_counter()
            rec = self._rec
            if rec is not None:
                rec.rec(_flight.SPAN_B, self._nid["serve.pull.ep"],
                        0.0, idx)
            try:
                blobs = self._bulk_client(idx).get_bytes_many(
                    [keys[p] for p in positions])
                for p, b in zip(positions, blobs):
                    out[p] = b
                if self._pace_mbps > 0.0:
                    # bench/test hook: model a per-endpoint link capacity.
                    # Groups sleep out their byte budget CONCURRENTLY, the
                    # way NIC-bound pulls overlap across real shard hosts.
                    nbytes = sum(len(b) for b in blobs if b)
                    time.sleep(max(0.0, nbytes / (self._pace_mbps * 1e6)
                                   - (time.perf_counter() - t0)))
                if rec is not None:
                    rec.rec(_flight.SPAN_E, self._nid["serve.pull.ep"],
                            float(sum(len(b) for b in blobs if b)), idx)
                    # flow finishes pair with the publisher's starts by
                    # the key-derived id: the cross-process arrow
                    for p, b in zip(positions, blobs):
                        if b is not None and len(b):
                            rec.rec(_flight.FLOW_F,
                                    self._nid["serve.snap"], float(len(b)),
                                    _snap.trace_flow_id(keys[p]))
            except (OSError, RuntimeError) as exc:
                if rec is not None:
                    rec.rec(_flight.SPAN_E, self._nid["serve.pull.ep"],
                            -1.0, idx)
                self._drop_bulk_client(idx)
                errs.append(f"{self._endpoints[idx][0]}:"
                            f"{self._endpoints[idx][1]}: {exc}")

        if len(groups) == 1:
            idx, positions = next(iter(groups.items()))
            pull_group(idx, positions)
        else:
            workers = [threading.Thread(target=pull_group, args=(i, ps),
                                        daemon=True)
                       for i, ps in groups.items()]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
        if errs:
            raise OSError("snapshot pull failed on "
                          f"{len(errs)}/{len(groups)} endpoint group(s): "
                          + "; ".join(errs))
        return out

    def _maybe_pull(self) -> None:
        ver = _snap.current_version(self._cl)
        if ver > self._fence_ver:
            self._fence_ver = ver   # staleness baseline, even when caught up
        if ver <= self._version or ver == 0:
            return
        if self._meta is None:
            self._meta = _snap.fetch_meta(self._cl)
            if self._meta is None:
                return  # fence moved but meta not visible yet; next poll
        t0 = time.perf_counter()
        rec = self._rec
        if rec is not None:
            rec.rec(_flight.SPAN_B, self._nid["serve.pull"], 0.0, ver)
        try:
            got = _snap.fetch_snapshot(self._cl, meta=self._meta,
                                       pull=self.pull_blobs)
        except (OSError, RuntimeError) as exc:
            self._stats["pull_failures"] += 1
            if rec is not None:
                rec.rec(_flight.SPAN_E, self._nid["serve.pull"], -1.0, ver)
                if not self._failover_open:
                    # opened on the first failed attempt, closed when a
                    # successor answers: the trace's failover span
                    self._failover_open = True
                    rec.rec(_flight.SPAN_B, self._nid["serve.failover"],
                            0.0, ver)
            logger.warning("serve client: snapshot pull failed (%s); "
                           "keeping version %d", exc, self._version)
            return
        if rec is not None:
            rec.rec(_flight.SPAN_E, self._nid["serve.pull"], 1.0, ver)
        if got is None:
            return
        leaves, got_ver, wire = got
        dt = max(1e-9, time.perf_counter() - t0)
        with self._mu:
            if got_ver <= self._version:
                return  # raced with a concurrent refresh
            self._params = leaves
            self._version = got_ver
            self._stats["swaps"] += 1
            self._stats["pulls"] += 1
            self._stats["wire_bytes"] += wire
            self._stats["pull_mbps"] = wire / dt / 1e6
        if rec is not None and self._failover_open:
            self._failover_open = False
            rec.rec(_flight.SPAN_E, self._nid["serve.failover"],
                    0.0, got_ver)
        self._ready.set()

    # -- poller ------------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._maybe_pull()
            except (OSError, RuntimeError, ValueError) as exc:
                self._stats["pull_failures"] += 1
                logger.warning("serve client: poll failed (%s)", exc)
            try:
                self._update_health()
            except (OSError, RuntimeError):
                pass
            if self._slos or self._trace:
                try:
                    self._slo_tick()
                except Exception as exc:  # noqa: BLE001 — telemetry only
                    logger.debug("serve client: slo tick failed (%s)", exc)
            self._stop.wait(self._poll_s)

    def _slo_tick(self) -> None:
        """Per-poll SLO/trace bookkeeping: refresh the latency/staleness
        percentile gauges and the per-phase breakdown gauges, run one
        sampling pass of this client's own time-series store (burn-rate
        evaluation lives there), and publish it under the serve-client
        rank band so the trainer's ``bf.ts.<rank>`` keys stay untouched."""
        if self._slos:
            n = min(self._lat_n, _PCT_RING)
            if n:
                w = self._lat_ring[:n]
                _metrics.gauge("slo.request_p50_us").set(
                    float(np.percentile(w, 50)))
                _metrics.gauge("slo.request_p99_us").set(
                    float(np.percentile(w, 99)))
            m = min(self._stal_n, _PCT_RING)
            if m:
                _metrics.gauge("slo.staleness_p99_ver").set(
                    float(np.percentile(self._stal_ring[:m], 99)))
        if self._trace:
            rep = _flight.serve_report()
            if rep:
                for p, st in rep["phases"].items():
                    _metrics.gauge("slo.phase." + p + ".p50_us").set(
                        st["p50_us"])
                    _metrics.gauge("slo.phase." + p + ".p99_us").set(
                        st["p99_us"])
        if self._ts is not None:
            self._publish_ts()

    def _publish_ts(self) -> None:
        from ..runtime import timeseries as _ts

        now = time.time()
        if now - self._ts._last_sample < 0.9:
            return
        self._ts.sample(now)
        interval = max(1.0, self._poll_s)
        if now - self._ts._last_publish < interval:
            return
        rank = _ts.SERVE_TS_RANK_BASE + max(0, self._cid)
        doc = self._ts.build_doc(rank, 0, now, interval)
        try:
            self._cl.put_bytes(_ts.TS_KEY_FMT.format(rank=rank),
                               _ts.pack_doc(doc))
            # unlike the trainer band, an empty blob is written on clear
            # so a consumer can see the alert lifecycle end
            self._cl.put_bytes(
                _ts.ALERTS_KEY_FMT.format(rank=rank),
                zlib.compress(json.dumps(doc["alerts"]).encode())
                if doc["alerts"] else b"")
            self._ts._last_publish = now
        except (OSError, RuntimeError):
            pass

    def _update_health(self) -> None:
        if hasattr(self._cl, "poll_shard_health"):
            # drives the router's dead -> rejoined -> adopt-new-address
            # cycle; without a periodic probe a shard that moved ports
            # would stay dead in this client's view forever
            self._cl.poll_shard_health()
        h: dict = {}
        ts = _snap._get_float(self._cl, _snap.PUB_TS_KEY)
        h["publish_lag_s"] = max(0.0, time.time() - ts) if ts > 0 else None
        h["mailbox_frac"] = self._mailbox_frac()
        h["alerts"] = self._alert_count()
        self._health = h
        if self._cid >= 0:
            _snap._put_float(
                self._cl, _snap.CLIENT_HB_FMT.format(cid=self._cid),
                time.time())

    def _mailbox_frac(self) -> float:
        cap = self._mailbox_cap
        if cap is None:
            # the serving process publishes (cap + 1) at startup; fall
            # back to this process's own knob when it predates the key
            try:
                v = int(self._cl.get("bf.cp.mailbox_cap_bytes"))
            except (OSError, RuntimeError):
                v = 0
            if v > 0:
                cap = v - 1
            else:
                from ..runtime.control_plane import mailbox_cap_bytes
                cap = mailbox_cap_bytes()
            self._mailbox_cap = cap
        if cap <= 0:
            return 0.0
        worst = 0
        if hasattr(self._cl, "server_stats_all"):
            for _, st in self._cl.server_stats_all():
                if st:
                    worst = max(worst, int(st.get("mailbox_bytes", 0)))
        else:
            st = self._cl.server_stats()
            worst = int(st.get("mailbox_bytes", 0)) if st else 0
        return worst / float(cap)

    def _alert_count(self) -> int:
        from ..runtime.timeseries import ALERTS_KEY_FMT

        try:
            world = max(1, int(self._cl.get("bf.metrics.world")))
        except (OSError, RuntimeError):
            return 0
        n = 0
        for r in range(min(world, 64)):
            try:
                blob = self._cl.get_bytes(ALERTS_KEY_FMT.format(rank=r))
            except (OSError, RuntimeError):
                continue
            if not blob:
                continue
            try:
                import json
                n += len(json.loads(zlib.decompress(bytes(blob))))
            except (ValueError, zlib.error):
                n += 1  # unreadable alert blob still counts as one
        return n

    # -- admission + batching ----------------------------------------------

    def admission(self) -> Tuple[str, str]:
        """(verdict, reason): ``accept`` | ``queue`` | ``shed``."""
        depth = self._q.qsize()
        if depth >= self._qmax:
            return "shed", "queue_full"
        h = self._health
        if h.get("mailbox_frac", 0.0) > 0.8:
            return "shed", "mailbox"
        if not self._ready.is_set():
            return "queue", "not_ready"
        if depth >= self._qsoft:
            return "queue", "queue_depth"
        lag = h.get("publish_lag_s")
        if lag is not None and lag > self._stale_s:
            return "queue", "publish_lag"
        if h.get("alerts", 0) > 0:
            return "queue", "alerts"
        return "accept", ""

    def submit(self, example: np.ndarray) -> Future:
        """Admit one example; the future resolves to its model output.

        Raises :class:`RequestShed` when the gate sheds. A ``queue``
        verdict still admits (counted in ``stats()['queued']``)."""
        if self._model_fn is None:
            raise RuntimeError("ServeClient was built without a model_fn")
        rec, tid, t0 = self._rec, 0, time.perf_counter()
        if rec is not None:
            tid = (self._tid_base
                   + next(self._tid_iter)) & 0x7FFFFFFFFFFFFFFF
            self._m_traced.inc()
            rec.rec(_flight.SPAN_B, self._nid["serve.req"], 0.0, tid)
            rec.rec(_flight.SPAN_B, self._nid["serve.admit"], 0.0, tid)
        verdict, reason = self.admission()
        if rec is not None:
            rec.rec(_flight.SPAN_E, self._nid["serve.admit"],
                    _ADMIT_CODE.get(verdict, -1.0), tid)
        if verdict == "shed":
            self._stats["shed"] += 1
            self._slo_shed()
            if rec is not None:
                rec.rec(_flight.SPAN_E, self._nid["serve.req"], -1.0, tid)
            raise RequestShed(
                f"request shed by admission control ({reason})", reason)
        fut: Future = Future()
        if rec is not None:
            rec.rec(_flight.SPAN_B, self._nid["serve.queue"], 0.0, tid)
        try:
            self._q.put_nowait((np.asarray(example), fut, tid, t0))
        except _queue.Full:
            self._stats["shed"] += 1
            self._slo_shed()
            if rec is not None:
                rec.rec(_flight.SPAN_E, self._nid["serve.queue"], 0.0, tid)
                rec.rec(_flight.SPAN_E, self._nid["serve.req"], -1.0, tid)
            raise RequestShed("request shed by admission control "
                              "(queue_full)", "queue_full") from None
        self._stats["queued" if verdict == "queue" else "accepted"] += 1
        self._stats["requests"] += 1
        return fut

    def _slo_shed(self) -> None:
        if not self._slos:
            return
        self._m_req.inc()
        self._m_shed.inc()
        b = self._m_breach.get("serve_avail")
        if b is not None:
            b.inc()

    def _slo_done(self, t0: float, ver: int) -> None:
        if not self._slos:
            return
        lat_us = (time.perf_counter() - t0) * 1e6
        stale = float(max(0, self._fence_ver - ver))
        self._lat_ring[self._lat_n % _PCT_RING] = lat_us
        self._lat_n += 1
        self._stal_ring[self._stal_n % _PCT_RING] = stale
        self._stal_n += 1
        self._m_req.inc()
        self._m_lat_h.observe(lat_us)
        self._m_stal_h.observe(stale)
        for o in self._slos:
            if o.name in ("serve_p50", "serve_p99"):
                if lat_us > o.target:
                    self._m_breach[o.name].inc()
            elif o.name == "serve_staleness" and stale > o.target:
                self._m_breach[o.name].inc()

    def infer(self, example: np.ndarray,
              timeout: Optional[float] = None) -> np.ndarray:
        """``submit`` + block on the result."""
        return self.submit(example).result(timeout)

    def _trace_dequeue(self, item) -> None:
        rec = self._rec
        if rec is None:
            return
        tid = item[2]
        rec.rec(_flight.SPAN_E, self._nid["serve.queue"], 0.0, tid)
        rec.rec(_flight.SPAN_B, self._nid["serve.linger"], 0.0, tid)

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            if not self._ready.wait(timeout=self._poll_s):
                continue
            try:
                first = self._q.get(timeout=self._poll_s)
            except _queue.Empty:
                continue
            self._trace_dequeue(first)
            batch = [first]
            deadline = time.monotonic() + self._linger_s
            while len(batch) < self._batch_max:
                left = deadline - time.monotonic()
                try:
                    item = self._q.get(
                        timeout=max(0.0, left)) if left > 0 \
                        else self._q.get_nowait()
                except _queue.Empty:
                    break
                self._trace_dequeue(item)
                batch.append(item)
            with self._mu:
                params = self._params
                served_ver = self._version
            rec = self._rec
            if rec is not None:
                for _, _, tid, _ in batch:
                    rec.rec(_flight.SPAN_E, self._nid["serve.linger"],
                            0.0, tid)
                    rec.rec(_flight.SPAN_B, self._nid["serve.decode"],
                            0.0, tid)
            xs = np.stack([x for x, _, _, _ in batch])
            try:
                ys = self._model_fn(params, xs)
            except Exception as exc:  # noqa: BLE001 — fail the futures
                for _, fut, tid, _ in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                    if rec is not None:
                        rec.rec(_flight.SPAN_E, self._nid["serve.decode"],
                                -1.0, tid)
                        rec.rec(_flight.SPAN_E, self._nid["serve.req"],
                                -1.0, tid)
                continue
            if rec is not None:
                for _, _, tid, _ in batch:
                    rec.rec(_flight.SPAN_E, self._nid["serve.decode"],
                            0.0, tid)
            self._stats["batches"] += 1
            for i, (_, fut, tid, t0) in enumerate(batch):
                if not fut.done():
                    fut.set_result(np.asarray(ys)[i])
                if rec is not None:
                    # span-end `a` = the answering snapshot version: the
                    # lineage link every consumer resolves through
                    rec.rec(_flight.SPAN_E, self._nid["serve.req"],
                            float(served_ver), tid)
                self._slo_done(t0, served_ver)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        out = dict(self._stats)
        out["version"] = self.version()
        out["queue_depth"] = self._q.qsize()
        out["publish_lag_s"] = self._health.get("publish_lag_s")
        out["staleness_ver"] = max(0, self._fence_ver - out["version"])
        return out


def serve_client(model_fn: Optional[Callable] = None,
                 endpoints: Optional[Sequence[Tuple[str, int]]] = None,
                 **kw) -> ServeClient:
    """Attach a :class:`ServeClient` to the job's control plane.

    Endpoint resolution mirrors ``bfrun --status``: explicit
    ``endpoints``, else ``BLUEFOG_CP_HOSTS``, else
    ``BLUEFOG_CP_HOST``/``BLUEFOG_CP_PORT``. The secret defaults to
    ``BLUEFOG_CP_SECRET``.
    """
    if endpoints is None:
        from ..runtime.router import parse_endpoints

        spec = knob_env("BLUEFOG_CP_HOSTS")
        if spec:
            endpoints = parse_endpoints(spec)
        else:
            host = knob_env("BLUEFOG_CP_HOST")
            port = knob_env("BLUEFOG_CP_PORT")
            if not host or not port:
                raise RuntimeError(
                    "serve_client: control-plane address unknown; pass "
                    "endpoints=[(host, port)] or set BLUEFOG_CP_HOSTS / "
                    "BLUEFOG_CP_HOST+BLUEFOG_CP_PORT")
            endpoints = [(host, int(port))]
    kw.setdefault("secret", knob_env("BLUEFOG_CP_SECRET") or "")
    return ServeClient(endpoints, model_fn, **kw)
