"""Serving plane: versioned snapshot distribution + read-only inference.

The training side of the repo gossips *windows*; this package is the read
side (ROADMAP "Serving plane", docs/serving.md). Training ranks publish
**versioned, immutable model snapshots** over the existing KV/striped-get
wire (``bf.serve.snap.<ver>.<shard>`` + a monotone ``bf.serve.ver``
commit fence written only after every shard landed), and external
processes attach with a raw control-plane client — no mesh join, no jax
anywhere on the fetch path — to pull them concurrently across the
control-plane shards, hot-swap weights on a version bump, and serve
batched inference behind an admission-control gate driven by the live
telemetry plane.

Import discipline: everything under ``bluefog_tpu.serving`` is
numpy-only. A standalone serving process uses the same lean bootstrap as
``scripts/cp_soak.py`` (stub parent packages, then import
``bluefog_tpu.serving.client``); inside a training job,
``bf.serve_client()`` re-exports :func:`serve_client`.
"""

from .snapshot import (  # noqa: F401
    GC_FLOOR_KEY,
    META_KEY,
    PUB_STEP_KEY,
    PUB_TS_KEY,
    SNAP_KEY_FMT,
    VER_KEY,
    SnapshotGone,
    SnapshotMeta,
    SnapshotPublisher,
    current_version,
    fetch_meta,
    fetch_snapshot,
    read_serve_status,
    serve_shard_count,
)
from .client import RequestShed, ServeClient, serve_client  # noqa: F401

__all__ = [
    "SNAP_KEY_FMT", "VER_KEY", "META_KEY", "PUB_TS_KEY", "PUB_STEP_KEY",
    "GC_FLOOR_KEY", "SnapshotMeta", "SnapshotPublisher", "SnapshotGone",
    "current_version", "fetch_meta", "fetch_snapshot", "read_serve_status",
    "serve_shard_count", "ServeClient", "RequestShed", "serve_client",
]
