"""Benchmark: ResNet-50 decentralized training throughput.

Port of the reference harness (examples/pytorch_benchmark.py: synthetic
ImageNet batches, 10 warmup batches, then 10 iterations x 10 batches). The
timed window covers all 100 batches and is closed by ONE host transfer (the
per-iteration sync of earlier rounds charged remote-tunnel latency, not
chip time, to the metric — see PERF.md). It runs the flagship fused step —
per-chip grad -> SGD-momentum update -> Expo-2 neighbor averaging — over all
available chips. Baseline for vs_baseline: the reference's published
`Total img/sec on 16 GPU(s): 4310.6` => 269.4 img/sec per V100
(docs/performance.rst:20-24). Batch is 128/chip (the reference uses 64/V100;
128 keeps the v5e MXU fed — 64 leaves ~15% throughput on the table and the
reference's own harness exposes --batch-size for exactly this reason).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import tempfile
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

import bluefog_tpu as bf
from bluefog_tpu.models import ResNet50
from bluefog_tpu.utils import prefetch_to_device

BATCH_PER_CHIP = 128
IMAGE = 224
WARMUP = 10
ITERS = 10
BATCHES_PER_ITER = 10
BASELINE_IMG_SEC_PER_DEVICE = 4310.6 / 16  # reference 16xV100 result


def setup(batch_per_chip: int = BATCH_PER_CHIP, synthetic_batch: bool = True):
    """Build the benchmark step: (opt, state, batch, sync). Caller owns
    ``bf.shutdown()``. Shared with scripts/batch_sweep.py so batch-size
    probes measure exactly the benchmarked step. ``synthetic_batch=False``
    skips building the device-resident batch (host-data mode feeds its own
    — no point holding 77 MB/chip of unused HBM)."""
    # fail fast on a dead backend BEFORE the first jax.devices() touch —
    # covers every setup() caller (bench main, scripts/batch_sweep.py)
    _require_live_backend()
    n = len(jax.devices())
    topo = bf.topology_util.ExponentialTwoGraph(n) if n > 1 else \
        bf.topology_util.FullyConnectedGraph(1)
    bf.init(topology_fn=lambda size: topo)

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((batch_per_chip, IMAGE, IMAGE, 3), jnp.float32)
    variables = model.init(rng, sample, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def loss_fn(p, ms, batch):
        images, labels = batch
        if images.dtype == jnp.uint8:
            # host-fed path ships uint8 (4x fewer wire bytes than f32, the
            # standard input-pipeline format); normalize on device
            images = images.astype(jnp.float32) / 127.5 - 1.0
        logits, updates = model.apply(
            {"params": p, "batch_stats": ms}, images, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, (updates["batch_stats"], {})

    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), loss_fn, with_model_state=True)
    state = opt.init(params, model_state=batch_stats)

    batch = None
    if synthetic_batch:
        images = jax.device_put(
            jax.random.normal(rng, (n, batch_per_chip, IMAGE, IMAGE, 3),
                              jnp.float32),
            bf.rank_sharding(bf.mesh()))
        labels = jax.device_put(
            jnp.zeros((n, batch_per_chip), jnp.int32),
            bf.rank_sharding(bf.mesh()))
        batch = (images, labels)

    def sync(m):
        # A host transfer is the only reliable completion barrier over the
        # remote-device tunnel (block_until_ready can return early there).
        return float(np.asarray(m["loss"])[0])

    return opt, state, batch, sync


def host_batch_pool(n: int, batch_per_chip: int, pool: int = 4,
                    image: int = IMAGE):
    """Endless cycle over ``pool`` distinct HOST (numpy) uint8 batches —
    the stand-in for a real data loader (the reference cycles a fake
    torchvision dataset the same way, pytorch_benchmark.py)."""
    rng = np.random.default_rng(7)
    batches = [
        (rng.integers(0, 256, (n, batch_per_chip, image, image, 3),
                      dtype=np.uint8),
         rng.integers(0, 1000, (n, batch_per_chip), dtype=np.int32))
        for _ in range(pool)
    ]
    return itertools.cycle(batches)


def _require_live_backend(timeout_s: float = 180.0) -> None:
    """Fail fast (exit 3, stderr diagnosis) when the accelerator backend
    cannot initialize — on this dev box the chip sits behind a remote
    tunnel whose outage otherwise turns the benchmark into an infinite
    hang inside jax.devices(). The probe runs in a SUBPROCESS: the plugin's
    C init blocks holding the GIL, so an in-process watchdog thread could
    never fire."""
    import subprocess
    import sys

    from bluefog_tpu.runtime.config import timeout_from_env

    timeout_s = timeout_from_env("BLUEFOG_BENCH_INIT_TIMEOUT", timeout_s)
    if timeout_s <= 0:  # explicit opt-out: skip the probe's init cost
        return
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True)
        if r.returncode == 0:
            return
        detail = r.stderr.decode(errors="replace")[-400:]
    except subprocess.TimeoutExpired:
        detail = f"probe did not finish within {timeout_s:.0f}s"
    print("bench: accelerator backend failed to initialize (remote-TPU "
          f"tunnel down?); aborting instead of hanging. {detail}",
          file=sys.stderr)
    raise SystemExit(3)


def main(host_data: bool = False, prefetch: int = 2,
         steps_scale: float = 1.0) -> None:
    opt, state, batch, sync = setup(synthetic_batch=not host_data)
    iters = max(1, round(ITERS * steps_scale))

    if host_data:
        # real host->HBM traffic: uint8 batches from a host pool, device_put
        # kept `prefetch` deep so the copy of batch t+1 overlaps step t
        n = len(jax.devices())
        feed = prefetch_to_device(
            host_batch_pool(n, BATCH_PER_CHIP), size=prefetch,
            sharding=bf.rank_sharding(bf.mesh()))
        metric = "resnet50_train_img_per_sec_per_chip_hostfeed"
    else:
        feed = itertools.repeat(batch)
        metric = "resnet50_train_img_per_sec_per_chip"

    for _ in range(WARMUP):
        state, metrics = opt.step(state, next(feed))
    sync(metrics)

    # One timed window over all iters x BATCHES_PER_ITER steps, closed by a
    # single host sync. A per-iteration sync would charge ~64 ms of tunnel
    # round-trip latency to every 10 batches (~12% of the measurement) —
    # an artifact of the remote-device link, not the chip. The reference's
    # harness never fully drains the CUDA queue per iteration either
    # (pytorch_benchmark.py timeit over async launches); the single final
    # transfer here drains ALL device work, so the window is honest.
    t0 = time.perf_counter()
    for _ in range(iters):
        for _ in range(BATCHES_PER_ITER):
            state, metrics = opt.step(state, next(feed))
    sync(metrics)
    dt = time.perf_counter() - t0

    per_device = BATCH_PER_CHIP * BATCHES_PER_ITER * iters / dt
    print(json.dumps({
        "metric": metric,
        "value": round(per_device, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_device / BASELINE_IMG_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host-data", action="store_true",
                   help="feed uint8 batches from host memory through the "
                        "double-buffered prefetcher (real host->HBM traffic)")
    p.add_argument("--prefetch", type=int, default=2,
                   help="in-flight host transfers; note the timed window "
                        "has no per-step sync, so async step dispatch "
                        "already overlaps transfers with queued compute — "
                        "1 vs 2 is a queue-depth knob here, not a clean "
                        "overlap A/B (examples/resnet.py, which syncs per "
                        "step, shows the prefetch effect directly)")
    p.add_argument("--steps-scale", type=float, default=1.0,
                   help="scale the timed iteration count (host-data runs on "
                        "a slow dev tunnel may want fewer steps)")
    a = p.parse_args()
    main(host_data=a.host_data, prefetch=a.prefetch,
         steps_scale=a.steps_scale)
